"""Serving plane (round 10): engine buckets, micro-batching, hot swap, chaos,
and the gRPC front door.

The load-bearing claims, each pinned here:

- bucket programs are exact at bucket shapes and pad lanes cannot perturb
  real lanes (inference-mode BN is per-sample independent);
- tiled sliding-window inference is byte-deterministic and degenerates to
  the plain bucket program for a single-tile image;
- the batcher's request-boundary barrier means a batch straddling a weight
  swap answers ENTIRELY from one version (no torn reads), and post-swap
  outputs are BIT-identical to a cold start of the same weights;
- injected serving faults (swap mid-flight, device loss mid-batch) drop
  zero requests;
- the hand-regenerated transport_pb2 serving descriptors cannot drift from
  transport.proto (the regen script's DescriptorProtos are compared against
  both the live module and the .proto text).
"""

import threading

import numpy as np
import pytest

pytestmark = pytest.mark.serve

TINY_KW = dict(
    img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
BUCKETS = (16, 32)


@pytest.fixture(scope="module")
def stack():
    """One compiled engine + two weight versions shared by the module (the
    bucket compiles dominate test cost; every test takes fresh batchers /
    managers over the same engine)."""
    import jax

    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import InferenceEngine

    model_config = ModelConfig(**TINY_KW)
    serve_config = ServeConfig(
        bucket_sizes=BUCKETS, max_batch=4, max_delay_ms=10.0, tile_overlap=4
    )
    engine = InferenceEngine(model_config, serve_config)
    var0 = init_variables(jax.random.key(0), model_config)
    var1 = init_variables(jax.random.key(1), model_config)
    return engine, var0, var1


def _images(n, size, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)


# ---- streaming percentiles (obs satellite) ----


def test_streaming_percentiles_exact_until_capacity():
    from fedcrack_tpu.obs.metrics import StreamingPercentiles

    rng = np.random.default_rng(7)
    samples = rng.exponential(20.0, size=1000)
    sp = StreamingPercentiles(capacity=2048)
    for v in samples:
        sp.add(v)
    # Under capacity the reservoir holds everything: EXACTLY numpy.
    for q in (0.0, 50.0, 95.0, 99.0, 100.0):
        assert sp.percentile(q) == pytest.approx(
            float(np.percentile(samples, q)), rel=1e-12
        )
    s = sp.summary()
    assert s["count"] == 1000
    assert s["min"] == samples.min() and s["max"] == samples.max()
    assert s["mean"] == pytest.approx(samples.mean())
    assert s["p50"] == pytest.approx(float(np.percentile(samples, 50)))


def test_streaming_percentiles_bounded_and_sane_past_capacity():
    from fedcrack_tpu.obs.metrics import StreamingPercentiles

    sp = StreamingPercentiles(capacity=64, seed=3)
    samples = np.linspace(0.0, 1000.0, 5000)
    for v in samples:
        sp.add(v)
    assert sp.count == 5000
    assert len(sp._values) == 64  # memory stays bounded
    # Exact extremes/mean are tracked outside the reservoir; percentiles are
    # a uniform-sample estimate — loose sanity bounds, not exactness.
    s = sp.summary()
    assert s["min"] == 0.0 and s["max"] == 1000.0
    assert 300.0 < s["p50"] < 700.0
    assert s["p95"] > s["p50"]
    # Deterministic for a fixed (seed, insertion order).
    sp2 = StreamingPercentiles(capacity=64, seed=3)
    for v in samples:
        sp2.add(v)
    assert sp2.percentile(50.0) == sp.percentile(50.0)


def test_streaming_percentiles_empty_and_validation():
    from fedcrack_tpu.obs.metrics import StreamingPercentiles

    sp = StreamingPercentiles(capacity=8)
    assert sp.percentile(50.0) is None
    assert sp.summary()["p99"] is None and sp.summary()["count"] == 0
    with pytest.raises(ValueError):
        sp.percentile(101.0)
    with pytest.raises(ValueError):
        StreamingPercentiles(capacity=0)


# ---- engine: buckets, padding, tiling ----


def test_tile_plan_covers_and_is_deterministic():
    from fedcrack_tpu.serve.engine import tile_plan

    for extent, tile, overlap in [(100, 32, 8), (32, 32, 8), (97, 32, 0), (64, 32, 8)]:
        offs = tile_plan(extent, tile, overlap)
        assert offs == tile_plan(extent, tile, overlap)
        assert offs[0] == 0 and offs[-1] == extent - tile
        covered = np.zeros(extent, bool)
        for o in offs:
            covered[o : o + tile] = True
        assert covered.all()
        # every neighbor pair overlaps by at least `overlap` pixels
        for a, b in zip(offs, offs[1:]):
            assert b - a <= tile - overlap or b == extent - tile
    with pytest.raises(ValueError):
        tile_plan(16, 32, 8)
    with pytest.raises(ValueError):
        tile_plan(64, 32, 32)


def test_bucket_routing(stack):
    engine, _, _ = stack
    assert engine.bucket_for(16, 16) == 16
    assert engine.bucket_for(10, 14) == 16
    assert engine.bucket_for(17, 8) == 32
    assert engine.bucket_for(32, 32) == 32
    assert engine.bucket_for(33, 8) is None
    assert engine.n_tiles(32, 32) == 1
    assert engine.n_tiles(60, 32) == 2


def test_pad_lanes_do_not_perturb_real_lanes(stack):
    """A 1-lane submission padded to the compiled max_batch must return the
    SAME bytes as the same image inside a full batch — inference-mode BN uses
    running stats, so lanes are independent (the micro-batcher's padding
    contract)."""
    engine, var0, _ = stack
    dev0 = engine.prepare(var0)
    imgs = _images(4, 16, seed=1)
    full = engine.predict_bucket(dev0, imgs)
    solo = engine.predict_bucket(dev0, imgs[:1])
    np.testing.assert_array_equal(full[:1], solo)


def test_predict_image_pads_and_crops(stack):
    engine, var0, _ = stack
    dev0 = engine.prepare(var0)
    out = engine.predict_image(dev0, _images(1, 16, seed=2)[0][:10, :14])
    assert out.shape == (10, 14, 1)
    assert out.dtype == np.float32
    assert np.isfinite(out).all() and (0.0 <= out).all() and (out <= 1.0).all()


def test_tiled_byte_deterministic_and_single_tile_exact(stack):
    engine, var0, _ = stack
    dev0 = engine.prepare(var0)
    rng = np.random.default_rng(5)
    big = rng.integers(0, 256, (50, 70, 3), dtype=np.uint8)
    a = engine.predict_tiled(dev0, big)
    b = engine.predict_tiled(dev0, big)
    assert a.shape == (50, 70, 1)
    np.testing.assert_array_equal(a, b)  # byte-deterministic, run to run
    # A single-tile image (exactly the largest bucket) has blend weight 1
    # everywhere: the tiled path must equal the plain bucket program bytes.
    one = _images(1, 32, seed=6)
    tiled = engine.predict_tiled(dev0, one[0])
    direct = engine.predict_bucket(dev0, one)[0]
    np.testing.assert_array_equal(tiled, direct)


# ---- batcher: micro-batching, deadlines, swap barrier ----


def test_batcher_coalesces_into_one_batch(stack):
    from fedcrack_tpu.serve import MicroBatcher, StaticWeights

    engine, var0, _ = stack
    with MicroBatcher(
        engine, StaticWeights(engine.prepare(var0)), max_delay_ms=200.0
    ) as b:
        imgs = _images(4, 16, seed=7)
        futs = [b.submit(img) for img in imgs]
        results = [f.result(timeout=60) for f in futs]
        stats = b.stats()
    assert stats["completed"] == 4 and stats["batches"] == 1
    assert stats["per_bucket"] == {"16": 4, "32": 0}
    assert all(r.model_version == 0 for r in results)
    # The batch result must equal the engine's direct bytes for the batch.
    direct = engine.predict_bucket(engine.prepare(var0), imgs)
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r.probs, direct[i])


def test_batcher_rejects_non_bucket_shapes_and_closed(stack):
    from fedcrack_tpu.serve import MicroBatcher, StaticWeights

    engine, var0, _ = stack
    b = MicroBatcher(engine, StaticWeights(engine.prepare(var0)))
    with pytest.raises(ValueError, match="bucket shapes"):
        b.submit(np.zeros((20, 20, 3), np.uint8))
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(np.zeros((16, 16, 3), np.uint8))


def test_batcher_deadline_accounting(stack):
    from fedcrack_tpu.serve import MicroBatcher, StaticWeights

    engine, var0, _ = stack
    with MicroBatcher(engine, StaticWeights(engine.prepare(var0))) as b:
        # An already-expired deadline: still served (never dropped), counted.
        r = b.submit(_images(1, 16)[0], deadline_ms=1e-6).result(timeout=60)
        stats = b.stats()
    assert r.deadline_missed
    assert stats["deadline_missed"] == 1 and stats["completed"] == 1


def test_hot_swap_post_swap_bit_identical_to_cold_start(stack):
    """The tentpole pin: after a live swap to round-N weights, served bytes
    == a cold start of the same round's weights (same compiled program, same
    device values)."""
    from fedcrack_tpu.serve import MicroBatcher, ModelVersionManager

    engine, var0, var1 = stack
    imgs = _images(4, 16, seed=8)
    mgr = ModelVersionManager(engine, var0)
    with MicroBatcher(engine, mgr, max_delay_ms=200.0) as b:
        pre = [f.result(timeout=60) for f in [b.submit(i) for i in imgs]]
        assert mgr.install(1, var1)
        post = [f.result(timeout=60) for f in [b.submit(i) for i in imgs]]
    mgr.stop()
    assert all(r.model_version == 0 for r in pre)
    assert all(r.model_version == 1 for r in post)
    cold0 = engine.predict_bucket(engine.prepare(var0), imgs)
    cold1 = engine.predict_bucket(engine.prepare(var1), imgs)
    for i in range(4):
        np.testing.assert_array_equal(pre[i].probs, cold0[i])
        np.testing.assert_array_equal(post[i].probs, cold1[i])
    assert mgr.last_swap["to_version"] == 1 and mgr.last_swap["load_ms"] >= 0


def test_recompiles_stay_zero_across_hot_swap_via_metrics_scrape(stack):
    """Round-15 satellite: the serve plane's jit-cache stability is pinned
    through a REAL ``/metrics`` scrape, not just the in-object counter —
    `serve_recompiles_total` must read 0 over HTTP after traffic on both
    sides of a hot swap (a swap installs new weights, never a new program)."""
    from fedcrack_tpu.obs.promexp import MetricsExporter, sample_value, scrape
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.serve import MicroBatcher, ModelVersionManager
    from fedcrack_tpu.serve.engine import watch_recompiles

    engine, var0, var1 = stack
    imgs = _images(4, 16, seed=21)
    mgr = ModelVersionManager(engine, var0)
    # Warm the bucket program BEFORE the sentry marks steady state (the
    # module fixture usually did already; this makes the test order-proof).
    engine.predict_bucket(engine.prepare(var0), imgs)
    reg = MetricsRegistry()
    sentry = watch_recompiles(engine, registry=reg)
    if not sentry.deltas() and not type(sentry).supported(engine._fn):
        pytest.skip("this jax build exposes no jit cache size")
    with MetricsExporter(reg) as exporter:
        with MicroBatcher(engine, mgr, max_delay_ms=200.0) as b:
            [f.result(timeout=60) for f in [b.submit(i) for i in imgs]]
            assert mgr.install(1, var1)
            [f.result(timeout=60) for f in [b.submit(i) for i in imgs]]
        mgr.stop()
        parsed = scrape(exporter.url)
    assert sample_value(parsed, "serve_recompiles_total") == 0
    sentry.assert_steady()


def test_swap_mid_batch_no_torn_reads(stack):
    """A batch straddling a swap gets EXACTLY one version's outputs: the
    chaos hook installs v1 after the worker snapshotted v0, and the whole
    batch must still answer from v0 (the request-boundary barrier)."""
    from fedcrack_tpu.chaos import SERVE_SWAP_MIDFLIGHT, Fault, FaultPlan, ServeChaos
    from fedcrack_tpu.serve import MicroBatcher, ModelVersionManager

    engine, var0, var1 = stack
    imgs = _images(4, 16, seed=9)
    mgr = ModelVersionManager(engine, var0)
    chaos = ServeChaos(
        FaultPlan(faults=(Fault(kind=SERVE_SWAP_MIDFLIGHT, round=0),)),
        swap_hook=lambda: mgr.install(1, var1),
    )
    with MicroBatcher(engine, mgr, max_delay_ms=200.0, chaos=chaos) as b:
        batch = [f.result(timeout=60) for f in [b.submit(i) for i in imgs]]
        after = b.submit(imgs[0]).result(timeout=60)
    mgr.stop()
    # The straddled batch: entirely v0, byte-equal to v0 cold outputs.
    assert {r.model_version for r in batch} == {0}
    cold0 = engine.predict_bucket(engine.prepare(var0), imgs)
    for i, r in enumerate(batch):
        np.testing.assert_array_equal(r.probs, cold0[i])
    # The NEXT batch picks up the installed version.
    assert after.model_version == 1
    np.testing.assert_array_equal(
        after.probs, engine.predict_bucket(engine.prepare(var1), imgs[:1])[0]
    )
    assert mgr.version == 1


def test_injected_device_loss_drops_nothing(stack):
    from fedcrack_tpu.chaos import SERVE_DEVICE_LOSS, Fault, FaultPlan, ServeChaos
    from fedcrack_tpu.serve import MicroBatcher, ModelVersionManager

    engine, var0, _ = stack
    mgr = ModelVersionManager(engine, var0)
    chaos = ServeChaos(
        FaultPlan(faults=(Fault(kind=SERVE_DEVICE_LOSS, round=0),))
    )
    imgs = _images(4, 16, seed=10)
    with MicroBatcher(engine, mgr, max_delay_ms=200.0, chaos=chaos) as b:
        results = [f.result(timeout=60) for f in [b.submit(i) for i in imgs]]
        stats = b.stats()
    mgr.stop()
    assert len(results) == 4 and stats["completed"] == 4
    assert stats["failed"] == 0
    assert stats["batch_retries"] == 1  # one injected loss, one clean retry
    cold0 = engine.predict_bucket(engine.prepare(var0), imgs)
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r.probs, cold0[i])


def test_exhausted_retries_fail_loudly_not_silently(stack):
    from fedcrack_tpu.serve import MicroBatcher, StaticWeights
    from fedcrack_tpu.serve.batcher import MAX_BATCH_ATTEMPTS

    engine, var0, _ = stack

    class AlwaysDown:
        calls = 0

        def on_batch(self, bucket, batch_index, attempt):
            AlwaysDown.calls += 1
            raise RuntimeError("device permanently lost")

    with MicroBatcher(
        engine, StaticWeights(engine.prepare(var0)), chaos=AlwaysDown()
    ) as b:
        fut = b.submit(_images(1, 16)[0])
        with pytest.raises(RuntimeError, match="permanently lost"):
            fut.result(timeout=60)
        stats = b.stats()
    assert AlwaysDown.calls == MAX_BATCH_ATTEMPTS
    assert stats["failed"] == 1 and stats["completed"] == 0


# ---- hot swap: statefile / checkpoint watching ----


def test_manager_polls_statefile_and_ignores_stale(stack, tmp_path):
    from fedcrack_tpu.serve import ModelVersionManager, publish_statefile

    engine, var0, var1 = stack
    path = str(tmp_path / "server_state.msgpack")
    mgr = ModelVersionManager(
        engine, var0, initial_version=5, state_path=path, template=var0
    )
    assert mgr.poll_once() is False  # no file yet
    publish_statefile(path, var1, model_version=3)
    assert mgr.poll_once() is False  # stale (3 <= 5): never regress
    assert mgr.version == 5
    publish_statefile(path, var1, model_version=9)
    assert mgr.poll_once() is True
    assert mgr.version == 9
    out = engine.predict_bucket(mgr.snapshot()[1], _images(2, 16, seed=11))
    cold1 = engine.predict_bucket(engine.prepare(var1), _images(2, 16, seed=11))
    np.testing.assert_array_equal(out, cold1)
    mgr.stop()


def test_manager_survives_corrupt_statefile(stack, tmp_path):
    from fedcrack_tpu.serve import ModelVersionManager

    engine, var0, _ = stack
    path = tmp_path / "server_state.msgpack"
    path.write_bytes(b"\x00garbage not msgpack")
    mgr = ModelVersionManager(engine, var0, state_path=str(path), template=var0)
    assert mgr.poll_once() is False  # unreadable -> keep current, don't raise
    assert mgr.version == 0
    mgr.stop()


def test_manager_polls_checkpoint_dir(stack, tmp_path):
    from fedcrack_tpu.ckpt.manager import FedCheckpoint, FedCheckpointer
    from fedcrack_tpu.serve import ModelVersionManager

    engine, var0, var1 = stack
    ckpt_dir = str(tmp_path / "ckpt")
    with FedCheckpointer(ckpt_dir) as ckptr:
        ckptr.save(FedCheckpoint(current_round=2, model_version=2, variables=var1))
    mgr = ModelVersionManager(engine, var0, ckpt_dir=ckpt_dir, template=var0)
    assert mgr.poll_once() is True
    assert mgr.version == 2
    imgs = _images(2, 16, seed=12)
    np.testing.assert_array_equal(
        engine.predict_bucket(mgr.snapshot()[1], imgs),
        engine.predict_bucket(engine.prepare(var1), imgs),
    )
    mgr.stop()


def test_background_poll_thread_swaps_live(stack, tmp_path):
    from fedcrack_tpu.serve import ModelVersionManager, publish_statefile

    engine, var0, var1 = stack
    path = str(tmp_path / "state.msgpack")
    mgr = ModelVersionManager(
        engine, var0, state_path=path, poll_s=0.05, template=var0
    )
    with mgr:
        publish_statefile(path, var1, model_version=1)
        done = threading.Event()
        for _ in range(200):
            if mgr.version == 1:
                done.set()
                break
            threading.Event().wait(0.05)
        assert done.is_set(), "poll thread never installed the published model"
    assert mgr.last_swap["to_version"] == 1


# ---- gRPC front door ----


@pytest.fixture(scope="module")
def grpc_stack(stack):
    """In-process gRPC serving stack shared by the front-door tests."""
    from fedcrack_tpu.serve import (
        MicroBatcher,
        ModelVersionManager,
        ServeServer,
        ServeServerThread,
        ServeService,
    )

    engine, var0, _ = stack
    mgr = ModelVersionManager(engine, var0)
    batcher = MicroBatcher(engine, mgr, max_delay_ms=5.0)
    server = ServeServer(ServeService(engine, batcher, mgr), port=0)
    with ServeServerThread(server) as thread:
        yield thread.port, mgr, batcher
    batcher.close()
    mgr.stop()


def test_front_door_serves_all_routes_zero_drops(grpc_stack):
    """Closed-loop load over both buckets plus a non-bucket size (pad+crop
    route) through the real socket: every request answered, zero drops."""
    from fedcrack_tpu.tools.load_gen import run_load

    port, _, _ = grpc_stack
    summary = run_load(
        f"127.0.0.1:{port}",
        mode="closed",
        n_requests=12,
        concurrency=3,
        sizes=(16, 32),
        seed=0,
    )
    assert summary["completed"] == 12
    assert summary["dropped"] == 0 and summary["rejected"] == 0
    assert set(summary["per_size"]) == {"16x16", "32x32"}
    assert summary["latency_ms"]["count"] == 12
    assert summary["latency_ms"]["p99"] >= summary["latency_ms"]["p50"] > 0


def test_front_door_live_swap_two_versions_observed(grpc_stack, tmp_path):
    """The acceptance-shaped smoke, in-process: a hot swap lands mid-run and
    the client observes BOTH versions with zero drops."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.tools.load_gen import run_load

    port, mgr, _ = grpc_stack
    base = mgr.version
    var_new = init_variables(jax.random.key(42), ModelConfig(**TINY_KW))
    state = {"n": 0}

    def on_complete():
        state["n"] += 1
        if state["n"] == 8:
            assert mgr.install(base + 1, var_new)

    summary = run_load(
        f"127.0.0.1:{port}",
        mode="closed",
        n_requests=24,
        concurrency=2,
        sizes=(16, 32),
        seed=1,
        on_complete=on_complete,
    )
    assert summary["completed"] == 24 and summary["dropped"] == 0
    versions = {int(v) for v in summary["versions_observed"]}
    assert versions == {base, base + 1}


def test_front_door_open_loop_mode(grpc_stack):
    from fedcrack_tpu.tools.load_gen import run_load

    port, _, _ = grpc_stack
    summary = run_load(
        f"127.0.0.1:{port}",
        mode="open",
        n_requests=8,
        rate_rps=200.0,
        sizes=(16,),
        seed=2,
        timeout_s=60.0,
    )
    assert summary["completed"] == 8 and summary["dropped"] == 0


def test_front_door_rejects_bad_requests(grpc_stack):
    """Protocol-level rejects: wrong channel count, bad CRC, byte-count
    mismatch — each rejects THAT request with a reason, stream stays up."""
    import grpc as grpc_mod

    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import channel_options
    from fedcrack_tpu.serve.service import OK, PREDICT_PATH, REJECTED

    port, _, _ = grpc_stack
    channel = grpc_mod.insecure_channel(
        f"127.0.0.1:{port}", options=channel_options(8)
    )
    try:
        grpc_mod.channel_ready_future(channel).result(timeout=30)
        stub = channel.stream_stream(
            PREDICT_PATH,
            request_serializer=pb.PredictRequest.SerializeToString,
            response_deserializer=pb.PredictResponse.FromString,
        )
        img = _images(1, 16, seed=3)[0]
        reqs = [
            # 1) wrong channels
            pb.PredictRequest(
                request_id=1, height=16, width=16, channels=4,
                image=b"\0" * (16 * 16 * 4), offset=0, last=True,
            ),
            # 2) CRC mismatch
            pb.PredictRequest(
                request_id=2, height=16, width=16, channels=3,
                image=img.tobytes(), offset=0, last=True, crc32c=0xDEADBEEF,
            ),
            # 3) byte-count mismatch
            pb.PredictRequest(
                request_id=3, height=16, width=16, channels=3,
                image=img.tobytes()[:100], offset=0, last=True,
            ),
            # 4) a good one: the stream must still be serving
            pb.PredictRequest(
                request_id=4, height=16, width=16, channels=3,
                image=img.tobytes(), offset=0, last=True,
            ),
        ]
        responses = list(stub(iter(reqs)))
    finally:
        channel.close()
    by_id = {r.request_id: r for r in responses}
    assert by_id[1].status == REJECTED and "channels" in by_id[1].title
    assert by_id[2].status == REJECTED and "checksum" in by_id[2].title
    assert by_id[3].status == REJECTED
    assert by_id[4].status == OK
    assert len(by_id[4].mask) == 16 * 16
    mask = np.frombuffer(by_id[4].mask, np.uint8)
    assert set(np.unique(mask)) <= {0, 255}


def test_front_door_one_response_per_multichunk_reject(grpc_stack):
    """Exactly ONE response per request_id, even when a MIDDLE chunk of a
    multi-chunk request is rejected: later chunks of the dead request are
    swallowed (clients count responses 1:1 with requests — a second REJECTED
    for the same id would desynchronize every closed-loop client behind it)."""
    import grpc as grpc_mod

    from fedcrack_tpu.native import crc32c
    from fedcrack_tpu.serve.service import OK, PREDICT_PATH, REJECTED
    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import channel_options

    port, _, _ = grpc_stack
    img = _images(1, 16, seed=4)[0]
    blob = img.tobytes()
    third = len(blob) // 3

    def chunk(rid, piece, offset, last, bad_crc=False):
        return pb.PredictRequest(
            request_id=rid, height=16, width=16, channels=3,
            image=piece, offset=offset, last=last,
            crc32c=0xBAD0BAD0 if bad_crc else crc32c(piece),
        )

    reqs = [
        # request 1: 3 chunks, the MIDDLE one fails CRC; the tail chunk of
        # the now-dead request must produce no extra response.
        chunk(1, blob[:third], 0, False),
        chunk(1, blob[third : 2 * third], third, False, bad_crc=True),
        chunk(1, blob[2 * third :], 2 * third, True),
        # request 2: well-formed, must still be served in sync.
        chunk(2, blob, 0, True),
    ]
    channel = grpc_mod.insecure_channel(
        f"127.0.0.1:{port}", options=channel_options(8)
    )
    try:
        grpc_mod.channel_ready_future(channel).result(timeout=30)
        stub = channel.stream_stream(
            PREDICT_PATH,
            request_serializer=pb.PredictRequest.SerializeToString,
            response_deserializer=pb.PredictResponse.FromString,
        )
        responses = list(stub(iter(reqs)))
    finally:
        channel.close()
    assert [r.request_id for r in responses] == [1, 2]
    assert responses[0].status == REJECTED and "checksum" in responses[0].title
    assert responses[1].status == OK and len(responses[1].mask) == 16 * 16


# ---- generated pb2 cannot drift from transport.proto ----


def test_pb2_serving_descriptors_match_proto():
    """The checked-in transport_pb2 was regenerated by descriptor surgery
    (regen_pb2.py — no protoc in this image). Pin both directions: the live
    module's serving descriptors equal the regen script's DescriptorProtos,
    and every declared field appears in transport.proto's text with the same
    tag number."""
    import os
    import re

    from fedcrack_tpu.transport import regen_pb2
    from fedcrack_tpu.transport import transport_pb2 as pb

    for make, cls in [
        (regen_pb2._predict_request, pb.PredictRequest),
        (regen_pb2._predict_response, pb.PredictResponse),
        (regen_pb2._stream_open, pb.StreamOpen),
        (regen_pb2._stream_frame, pb.StreamFrame),
        (regen_pb2._stream_close, pb.StreamClose),
        (regen_pb2._stream_request, pb.StreamRequest),
        (regen_pb2._stream_response, pb.StreamResponse),
    ]:
        want = make()
        have = cls.DESCRIPTOR
        want_fields = {(f.name, f.number, f.type) for f in want.field}
        have_fields = {(f.name, f.number, f.type) for f in have.fields}
        assert want_fields == have_fields, cls.__name__

    svc = pb.DESCRIPTOR.services_by_name["ServePlane"]
    method = svc.methods_by_name["Predict"]
    assert method.input_type is pb.PredictRequest.DESCRIPTOR
    assert method.output_type is pb.PredictResponse.DESCRIPTOR
    stream = svc.methods_by_name["StreamPredict"]
    assert stream.input_type is pb.StreamRequest.DESCRIPTOR
    assert stream.output_type is pb.StreamResponse.DESCRIPTOR
    # Bidi: session requests stream in, per-frame responses stream out.
    want_stream = {
        m.name: (m.client_streaming, m.server_streaming)
        for m in regen_pb2._serve_plane().method
    }
    assert want_stream["StreamPredict"] == (True, True)
    # StreamRequest's oneof keeps open/frame/close mutually exclusive.
    assert [o.name for o in pb.StreamRequest.DESCRIPTOR.oneofs] == ["msg"]

    proto_path = os.path.join(os.path.dirname(regen_pb2.__file__), "transport.proto")
    with open(proto_path) as f:
        text = f.read()
    assert "service ServePlane" in text
    assert "rpc StreamPredict(stream StreamRequest) returns (stream StreamResponse)" in text
    for msg in (
        regen_pb2._predict_request(),
        regen_pb2._predict_response(),
        regen_pb2._stream_open(),
        regen_pb2._stream_frame(),
        regen_pb2._stream_close(),
        regen_pb2._stream_request(),
        regen_pb2._stream_response(),
    ):
        assert f"message {msg.name}" in text
        for field in msg.field:
            assert re.search(
                rf"\b{field.name}\s*=\s*{field.number}\b", text
            ), f"{msg.name}.{field.name} = {field.number} missing from transport.proto"


def test_regen_is_idempotent_against_checked_in_module():
    """Re-running the descriptor surgery over the checked-in module must be
    a no-op: everything it would add is already present."""
    from fedcrack_tpu.transport import regen_pb2

    fdp = regen_pb2.build_file_descriptor()
    assert fdp.SerializeToString() == regen_pb2.current_serialized_pb()


# ---- ServeConfig validation (configs satellite rides here too) ----


def test_serve_config_validation():
    from fedcrack_tpu.configs import ServeConfig

    with pytest.raises(ValueError, match="multiple of 16"):
        ServeConfig(bucket_sizes=(100,))
    with pytest.raises(ValueError, match="strictly increasing"):
        ServeConfig(bucket_sizes=(256, 128))
    with pytest.raises(ValueError, match="must not be empty"):
        ServeConfig(bucket_sizes=())
    with pytest.raises(ValueError, match="tile_overlap"):
        ServeConfig(bucket_sizes=(128,), tile_overlap=128)
    with pytest.raises(ValueError, match="max_batch"):
        ServeConfig(max_batch=0)
    with pytest.raises(ValueError, match="mesh_batch"):
        ServeConfig(max_batch=8, mesh_batch=3)
    with pytest.raises(ValueError, match="compute_dtype"):
        ServeConfig(compute_dtype="float16")
    with pytest.raises(ValueError, match="swap_poll_s"):
        ServeConfig(swap_poll_s=0.0)


# ---- runtime sanitizers on the serve plane (round 11) ----


def test_recompile_sentry_one_program_per_bucket_swap_is_pointer_flip(stack):
    """The serving compile contract, mechanically: a fresh engine compiles
    EXACTLY one program per bucket at warmup, steady-state traffic (full and
    padded partial batches) adds zero compiles, and a hot-swap install is a
    pointer flip — serving the new weights retraces nothing."""
    from fedcrack_tpu.analysis.sanitizers import RecompileSentry
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.serve import InferenceEngine
    from fedcrack_tpu.serve.hot_swap import ModelVersionManager

    _, var0, var1 = stack
    engine = InferenceEngine(
        ModelConfig(**TINY_KW),
        ServeConfig(bucket_sizes=BUCKETS, max_batch=4, max_delay_ms=10.0,
                    tile_overlap=4),
    )
    if not RecompileSentry.supported(engine._fn):
        pytest.skip("jit wrapper exposes no _cache_size on this jax build")
    sentry = RecompileSentry()
    sentry.watch("serve.predict", engine._fn)
    mgr = ModelVersionManager(engine, var0)
    with sentry.expect(compiles=len(BUCKETS)):
        engine.warmup(mgr.snapshot()[1])
    sentry.mark()
    for size in BUCKETS:
        engine.predict_bucket(mgr.snapshot()[1], _images(4, size))
        engine.predict_bucket(mgr.snapshot()[1], _images(2, size, seed=1))
    sentry.assert_steady()
    assert mgr.install(1, var1)
    for size in BUCKETS:
        out = engine.predict_bucket(mgr.snapshot()[1], _images(3, size, seed=2))
        assert out.shape == (3, size, size, 1)
    sentry.assert_steady()
    assert sentry.deltas() == {"serve.predict": 0}


def test_batcher_dispatch_no_implicit_transfers(stack):
    """The staged discipline of the dispatch path, armed for real: with
    jax.transfer_guard('disallow') active, a prepared snapshot serves whole
    batches end to end — every host<->device move on the serving path is an
    explicit device_put/device_get, so nothing can silently stall the
    pipeline with an implicit transfer."""
    import jax

    from fedcrack_tpu.analysis.sanitizers import no_implicit_transfers
    from fedcrack_tpu.serve.batcher import MicroBatcher, StaticWeights

    engine, var0, _ = stack
    dev0 = engine.prepare(var0)
    engine.warmup(dev0)  # compile outside the guard
    # The worker's inner dispatch op under a thread-local guard:
    with no_implicit_transfers():
        probs = engine.predict_bucket(dev0, _images(4, BUCKETS[0]))
    assert probs.shape == (4, BUCKETS[0], BUCKETS[0], 1)
    # Full batcher round-trip: the dispatch runs on worker THREADS, so the
    # guard must be installed process-wide for the span.
    jax.config.update("jax_transfer_guard", "disallow")
    try:
        with MicroBatcher(engine, StaticWeights(dev0, 0)) as batcher:
            futs = [
                batcher.submit(img)
                for img in _images(8, BUCKETS[1], seed=3)
            ]
            results = [f.result(timeout=60) for f in futs]
    finally:
        jax.config.update("jax_transfer_guard", "allow")
    assert len(results) == 8
    assert all(r.model_version == 0 for r in results)
