"""Cohort scale (round 13): time-multiplexed mesh groups + the tree.

The two non-negotiable gates:

1. **Grouped == single-group, BITWISE.** A cohort executed as ceil(C/G)
   sequential groups over a narrower mesh must reproduce the single-group
   C-wide round byte for byte — weights AND metrics — because the
   aggregation is an ordered client fold (one expression tree regardless
   of the split), not a psum (whose reduction order is backend-defined
   and does NOT compose across groups; measured in fedavg_mesh).
2. **The tree closes a 1,024-simulated-client round at O(fan-in) root
   memory**, every tier routing uploads through the shared
   decode_and_validate_update gate, trajectory bit-reproducible from the
   cohort seed.
"""

import hashlib
import os

import jax
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.pipeline import SamplePool
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.algorithms import fedavg, sample_cohort
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.fed.tree import (
    EdgeAggregator,
    partition_cohort,
    run_tree_federation,
)
from fedcrack_tpu.parallel import (
    CohortRound,
    build_federated_cohort_round,
    build_federated_round,
    make_mesh,
    run_cohort_federation,
    stack_client_data,
)
from fedcrack_tpu.train.local import create_train_state

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
STEPS, BATCH, COHORT_C, EPOCHS = 2, 4, 4, 2


@pytest.fixture(scope="module")
def cohort_data():
    per_client = [
        synth_crack_batch(STEPS * BATCH, img_size=TINY.img_size, seed=i)
        for i in range(COHORT_C)
    ]
    images, masks = stack_client_data(per_client, STEPS, BATCH)
    active = np.ones(COHORT_C, np.float32)
    # Distinct weights so the sample-weighted fold is load-bearing.
    n_samples = np.array([8.0, 16.0, 8.0, 24.0], np.float32)
    return images, masks, active, n_samples


@pytest.fixture(scope="module")
def variables():
    return create_train_state(jax.random.key(0), TINY).variables


@pytest.fixture(scope="module")
def oracle_result(cohort_data, variables):
    """The single-group mesh round over the full C-wide cohort — the
    byte-identity oracle for every group split."""
    mesh = make_mesh(COHORT_C, 1)
    round_fn = build_federated_round(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS
    )
    new_vars, metrics = round_fn(variables, *cohort_data)
    return (
        jax.tree_util.tree_map(np.asarray, new_vars),
        jax.tree_util.tree_map(np.asarray, metrics),
    )


@pytest.fixture(scope="module")
def cohort_round_g2():
    """The flagship grouped build: G=2 mesh, 2 groups, segments=2 (the
    'with segments > 0' arm of the acceptance pin), shared by the
    byte-identity test and the driver test."""
    mesh = make_mesh(2, 1)
    cr = build_federated_cohort_round(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=2
    )
    return mesh, cr


def _assert_trees_bytes_equal(got, want):
    gl = jax.tree_util.tree_leaves_with_path(got)
    wl = jax.tree_util.tree_leaves(want)
    assert len(gl) == len(wl)
    for (path, g), w in zip(gl, wl):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=jax.tree_util.keystr(path)
        )


# groups=2 (the load-bearing split: a real carry crosses real group
# boundaries on a narrower mesh) stays tier-1; groups=1 (degenerate: one
# group on the C-wide mesh, isolating the partial/finish program split)
# and groups=4 (G=1: every client its own dispatch) are slow-marked —
# each group count is a fresh set of XLA compiles and the tier-1
# wall-clock budget is the binding constraint (r7 precedent).
@pytest.mark.parametrize(
    "n_groups",
    [
        pytest.param(1, marks=pytest.mark.slow),
        2,
        pytest.param(4, marks=pytest.mark.slow),
    ],
)
def test_grouped_round_byte_identical(
    cohort_data, variables, oracle_result, cohort_round_g2, n_groups
):
    """Time-multiplexed execution is byte-identical (weights AND metrics)
    to the single-group mesh round, for groups in {1, 2, 4}, with
    segments=2 > 0."""
    if n_groups == 2:
        mesh, cr = cohort_round_g2
    else:
        g = COHORT_C // n_groups
        mesh = make_mesh(g, 1)
        cr = build_federated_cohort_round(
            mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=2
        )
    assert isinstance(cr, CohortRound)
    assert cr.group_size == COHORT_C // n_groups
    assert cr.n_groups(COHORT_C) == n_groups
    new_vars, metrics = cr(variables, *cohort_data)
    _assert_trees_bytes_equal(new_vars, oracle_result[0])
    _assert_trees_bytes_equal(metrics, oracle_result[1])


def test_cohort_driver_per_group_staging(
    cohort_data, variables, oracle_result, cohort_round_g2
):
    """run_cohort_federation — per-group staged slabs, explicit release,
    group timeline — reproduces the direct __call__ (and therefore the
    single-group oracle) byte for byte, and never holds more than ~2
    group slices of staged data."""
    mesh, cr = cohort_round_g2
    data_fn = lambda r: cohort_data
    out_vars, records = run_cohort_federation(cr, variables, data_fn, 1, mesh)
    _assert_trees_bytes_equal(out_vars, oracle_result[0])
    for k, leaf in records[0].metrics.items():
        np.testing.assert_array_equal(leaf, oracle_result[1][k], err_msg=k)
    rec = records[0]
    assert len(rec.segments) == 2  # ceil(4/2) group dispatches
    assert all(e["staged_bytes"] > 0 for e in rec.segments)
    group_bytes = rec.segments[0]["staged_bytes"]
    assert rec.staged_bytes == sum(e["staged_bytes"] for e in rec.segments)
    # 2-group-slice peak: group g+1 staged under group g, never a third.
    assert 0 < rec.max_live_staged_bytes <= 2 * group_bytes
    assert rec.max_live_staged_bytes == 2 * group_bytes


def test_cohort_driver_round_overlap_bit_identical(
    cohort_data, variables, cohort_round_g2
):
    """Round-overlap (round 14): overlapping round N+1's data/first-group
    staging AND first-group dispatch with round N's aggregation tail is
    pure host scheduling — weights and metrics byte-identical to the
    unoverlapped schedule, with the pipelined group visible in the
    consuming round's timeline."""
    mesh, cr = cohort_round_g2
    data_fn = lambda r: cohort_data
    v_plain, rec_plain = run_cohort_federation(cr, variables, data_fn, 2, mesh)
    v_pipe, rec_pipe = run_cohort_federation(
        cr, variables, data_fn, 2, mesh, round_overlap=True
    )
    _assert_trees_bytes_equal(v_pipe, v_plain)
    for rp, rq in zip(rec_plain, rec_pipe):
        for k, leaf in rq.metrics.items():
            np.testing.assert_array_equal(leaf, rp.metrics[k], err_msg=k)
    assert [e["group"] for e in rec_pipe[1].segments if e.get("pipelined")] == [0]
    assert not any(e.get("pipelined") for e in rec_pipe[0].segments)
    # The pipelined round still stages/accounts every group.
    assert rec_pipe[1].staged_bytes == rec_plain[1].staged_bytes


@pytest.mark.slow
def test_grouped_round_pads_ragged_cohort(variables):
    """C=3 on a G=2 mesh: the last group pads with an inactive zero-weight
    client — a bitwise no-op in the ordered fold — and the result equals
    the 3-wide single-group round exactly (weights and the [3] metrics)."""
    per_client = [
        synth_crack_batch(STEPS * BATCH, img_size=TINY.img_size, seed=10 + i)
        for i in range(3)
    ]
    images, masks = stack_client_data(per_client, STEPS, BATCH)
    active = np.ones(3, np.float32)
    n_samples = np.array([8.0, 16.0, 24.0], np.float32)
    mesh3 = make_mesh(3, 1)
    oracle = build_federated_round(
        mesh3, TINY, learning_rate=1e-3, local_epochs=EPOCHS
    )
    want_v, want_m = oracle(variables, images, masks, active, n_samples)
    mesh2 = make_mesh(2, 1)
    cr = build_federated_cohort_round(
        mesh2, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=1
    )
    assert cr.n_groups(3) == 2
    got_v, got_m = cr(variables, images, masks, active, n_samples)
    _assert_trees_bytes_equal(got_v, want_v)
    _assert_trees_bytes_equal(got_m, want_m)
    assert np.asarray(got_m["loss"]).shape == (3,)


@pytest.mark.slow
def test_cohort_round_resident_pool_matches_streamed(cohort_data, variables):
    """The resident cohort plane — per-group pool slices + gather plans —
    is byte-identical to the streamed grouped round over pool[idx] (the
    r9 contract, generalized to group grain), through the driver's
    per-group stage/release path."""
    images, masks, active, n_samples = cohort_data
    # Pool = the slab's samples, per client; the plan re-draws exactly the
    # slab layout so streamed and resident consume identical bytes.
    pool = SamplePool(
        images.reshape(COHORT_C, STEPS * BATCH, *images.shape[3:]),
        masks.reshape(COHORT_C, STEPS * BATCH, *masks.shape[3:]),
    )
    idx = np.broadcast_to(
        np.arange(STEPS * BATCH, dtype=np.int32).reshape(1, 1, STEPS, BATCH),
        (COHORT_C, EPOCHS, STEPS, BATCH),
    )
    mesh = make_mesh(2, 1)
    streamed = build_federated_cohort_round(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=1
    )
    want_v, want_m = streamed(variables, *cohort_data)
    resident = build_federated_cohort_round(
        mesh,
        TINY,
        learning_rate=1e-3,
        local_epochs=EPOCHS,
        segments=1,
        data_placement="resident",
    )
    got_v, got_m = resident(
        variables, (pool.images, pool.masks), idx, active, n_samples
    )
    _assert_trees_bytes_equal(got_v, want_v)
    _assert_trees_bytes_equal(got_m, want_m)
    # And through the driver, with per-group pool staging.
    data_fn = lambda r: (idx, active, n_samples)
    drv_v, records = run_cohort_federation(
        resident, variables, data_fn, 1, mesh, sample_pool=pool
    )
    _assert_trees_bytes_equal(drv_v, want_v)
    assert records[0].data_placement == "resident"
    assert all(e["staged_bytes"] > 0 for e in records[0].segments)


def test_cohort_driver_contract_mismatches(cohort_round_g2, variables):
    mesh, cr = cohort_round_g2
    pool = SamplePool(
        np.zeros((2, 4, 16, 16, 3), np.uint8), np.zeros((2, 4, 16, 16, 1), np.uint8)
    )
    with pytest.raises(ValueError, match="streamed"):
        run_cohort_federation(
            cr, variables, lambda r: None, 1, mesh, sample_pool=pool
        )
    with pytest.raises(ValueError, match="positive"):
        run_cohort_federation(cr, variables, lambda r: None, 0, mesh)


# ---------- seeded cohort sampling + partitioning ----------


def test_partition_cohort_deterministic_and_complete():
    cohort = sample_cohort(1000, 100, 3, seed=9)
    shards = partition_cohort(cohort, 8)
    assert len(shards) == 8
    flat = np.concatenate(shards)
    np.testing.assert_array_equal(flat, cohort)
    shards2 = partition_cohort(cohort, 8)
    for a, b in zip(shards, shards2):
        np.testing.assert_array_equal(a, b)
    # More edges than leaves: degenerate split, no empty shards.
    small = partition_cohort([1, 2], 8)
    assert [len(s) for s in small] == [1, 1]
    with pytest.raises(ValueError, match="n_edges"):
        partition_cohort(cohort, 0)


# ---------- the hierarchical aggregation tree ----------


def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _make_update(idx, r, base_blob, base_version):
    rng = np.random.default_rng([11, idx, r])
    base = tree_from_bytes(base_blob)
    tree = {
        "params": {
            "w": np.asarray(base["params"]["w"], np.float32)
            + rng.standard_normal((4, 4)).astype(np.float32) * 0.01
        }
    }
    return tree_to_bytes(tree), int(rng.integers(1, 50))


def test_tree_1024_clients_closes_at_fan_in_memory():
    """THE cohort-scale smoke: a 1,024-simulated-client round closes
    through a 2-level tree with root peak resident update blobs <= fan-in,
    and the whole trajectory is bit-reproducible from the cohort seed."""
    kwargs = dict(
        n_clients=4096,
        cohort_size=1024,
        n_rounds=2,
        n_edges=32,
        cohort_seed=5,
    )
    res = run_tree_federation(_vars(0.0), _make_update, **kwargs)
    assert res.state.phase == R.PHASE_FINISHED
    assert res.root_peak_blobs <= res.n_edges == 32
    assert res.edge_peak_blobs <= res.max_leaf_fan_in == 32
    assert res.leaf_updates == 2048 and res.leaf_rejections == 0
    # The whole point: root wire traffic is fan-in-sized, not cohort-sized.
    assert res.bytes_at_root < res.bytes_flat_equiv / 8
    res2 = run_tree_federation(_vars(0.0), _make_update, **kwargs)
    assert res.global_sha256 == res2.global_sha256
    assert res.cohorts == res2.cohorts
    # A different seed is a different trajectory (the seed is load-bearing).
    res3 = run_tree_federation(
        _vars(0.0), _make_update, **{**kwargs, "cohort_seed": 6}
    )
    assert res3.global_sha256 != res.global_sha256


def test_tree_matches_flat_fedavg():
    """One tree round == the flat sample-weighted FedAvg over the same
    cohort (weighted-mean associativity), to float re-association."""
    res = run_tree_federation(
        _vars(0.0),
        _make_update,
        n_clients=256,
        cohort_size=64,
        n_rounds=1,
        n_edges=8,
        cohort_seed=3,
    )
    cohort = sample_cohort(256, 64, 0, 3)
    base_blob = tree_to_bytes(_vars(0.0))
    trees, counts = [], []
    for i in cohort:
        blob, ns = _make_update(int(i), 0, base_blob, 0)
        trees.append(tree_from_bytes(blob))
        counts.append(ns)
    flat = fedavg(trees, counts)
    got = tree_from_bytes(res.state.global_blob)["params"]["w"]
    np.testing.assert_allclose(
        got, np.asarray(flat["params"]["w"]), rtol=0, atol=1e-6
    )


def test_edge_sanitizes_every_leaf_update():
    """Every tier routes through the shared acceptance gate: a NaN update,
    a wrong-shape tree and a truncated blob are all rejected AT THE EDGE
    (recorded, never averaged), and the partial equals the weighted mean
    of the clean leaves only."""
    template = tree_from_bytes(tree_to_bytes(_vars(0.0)))
    edge = EdgeAggregator("edge-0", template, quorum_fraction=0.5)
    edge.begin_round(1, tree_to_bytes(_vars(0.0)), 0, ["a", "b", "nan", "shape", "trunc"])
    assert edge.offer("a", tree_to_bytes(_vars(1.0)), 10)[0]
    bad_nan = {"params": {"w": np.full((4, 4), np.nan, np.float32)}}
    ok, reason = edge.offer("nan", tree_to_bytes(bad_nan), 10)
    assert not ok and "non-finite" in reason
    bad_shape = {"params": {"w": np.zeros((2, 2), np.float32)}}
    ok, reason = edge.offer("shape", tree_to_bytes(bad_shape), 10)
    assert not ok and "shape" in reason
    blob = tree_to_bytes(_vars(9.0))
    ok, reason = edge.offer("trunc", blob[: len(blob) // 2], 10)
    assert not ok and "undecodable" in reason
    ok, reason = edge.offer("outsider", tree_to_bytes(_vars(5.0)), 10)
    assert not ok and "not in this edge's shard" in reason
    assert edge.offer("b", tree_to_bytes(_vars(3.0)), 30)[0]
    assert not edge.quorum_met()  # 2 accepted < ceil(0.5 * 5) = 3
    assert sorted(edge.rejected) == ["nan", "shape", "trunc"]
    partial, total = edge.partial()
    got = tree_from_bytes(partial)["params"]["w"]
    np.testing.assert_allclose(got, (10 * 1.0 + 30 * 3.0) / 40, atol=1e-6)
    assert total == 40


def test_edge_quorum_is_k_of_n():
    template = tree_from_bytes(tree_to_bytes(_vars(0.0)))
    edge = EdgeAggregator("e", template, quorum_fraction=0.5)
    edge.begin_round(1, tree_to_bytes(_vars(0.0)), 0, ["a", "b", "c", "d"])
    assert edge.quorum == 2
    assert not edge.quorum_met()
    edge.offer("a", tree_to_bytes(_vars(1.0)), 1)
    assert not edge.quorum_met()
    edge.offer("b", tree_to_bytes(_vars(2.0)), 1)
    assert edge.quorum_met()


def test_edge_statefile_kill_restart_resumes_round(tmp_path):
    """An edge killed mid-round resumes the SAME round from its statefile:
    already-received updates intact, base preserved, and the completed
    partial is EXACTLY what the unkilled edge would have produced."""
    template = tree_from_bytes(tree_to_bytes(_vars(0.0)))
    path = str(tmp_path / "edge.msgpack")
    edge = EdgeAggregator("edge-7", template, state_path=path)
    base = tree_to_bytes(_vars(0.0))
    edge.begin_round(3, base, 2, ["a", "b", "c"])
    edge.offer("a", tree_to_bytes(_vars(1.0)), 10)
    edge.offer("b", tree_to_bytes(_vars(2.0)), 10)
    del edge  # the kill

    restored = EdgeAggregator.restore(path, template)
    assert restored is not None
    assert restored.edge_id == "edge-7"
    assert restored.round == 3 and restored.base_version == 2
    assert sorted(restored.received) == ["a", "b"]
    assert restored.leaves == frozenset({"a", "b", "c"})
    restored.offer("c", tree_to_bytes(_vars(6.0)), 20)
    partial, total = restored.partial()
    clean = EdgeAggregator("edge-7", template)
    clean.begin_round(3, base, 2, ["a", "b", "c"])
    clean.offer("a", tree_to_bytes(_vars(1.0)), 10)
    clean.offer("b", tree_to_bytes(_vars(2.0)), 10)
    clean.offer("c", tree_to_bytes(_vars(6.0)), 20)
    want, want_total = clean.partial()
    assert partial == want and total == want_total
    # Missing / corrupt statefiles degrade to None, never raise.
    assert EdgeAggregator.restore(str(tmp_path / "nope"), template) is None
    with open(path, "wb") as f:
        f.write(b"garbage")
    assert EdgeAggregator.restore(path, template) is None


def test_tree_with_compressed_edge_hop():
    """Edge→root re-encoding with the r12 codecs: the partial crosses as a
    CRC'd delta frame the root's existing frame decode + sanitation
    accepts, and the frame is smaller than the dense partial."""
    res = run_tree_federation(
        _vars(0.0),
        _make_update,
        n_clients=64,
        cohort_size=16,
        n_rounds=2,
        n_edges=4,
        cohort_seed=1,
        update_codec="int8",
    )
    assert res.state.phase == R.PHASE_FINISHED
    for entry in res.state.history:
        # The root saw FRAMES (codec recorded per edge) and accounted the
        # wire bytes separately from the decoded reconstruction. (On this
        # toy 4x4 tree the frame manifest outweighs the payload, so no
        # size inequality is asserted — the >=10x ratio at model scale is
        # test_compress/bench territory.)
        assert set(entry["codecs"].values()) == {"int8"}
        assert entry["bytes_received"] != entry["decoded_bytes_received"]
        assert entry["rejected"] == {}
    # Same federation, null codec: trajectories agree loosely (int8 is
    # quantized) but both close and reproduce deterministically.
    dense = run_tree_federation(
        _vars(0.0),
        _make_update,
        n_clients=64,
        cohort_size=16,
        n_rounds=2,
        n_edges=4,
        cohort_seed=1,
    )
    a = tree_from_bytes(res.state.global_blob)["params"]["w"]
    b = tree_from_bytes(dense.state.global_blob)["params"]["w"]
    np.testing.assert_allclose(a, b, atol=0.05)


def test_tree_statefiles_per_tier(tmp_path):
    """state_dir arms one statefile per edge; mid-federation they exist
    and restore."""
    res = run_tree_federation(
        _vars(0.0),
        _make_update,
        n_clients=32,
        cohort_size=8,
        n_rounds=1,
        n_edges=2,
        cohort_seed=2,
        state_dir=str(tmp_path),
    )
    assert res.state.phase == R.PHASE_FINISHED
    for e in range(2):
        path = os.path.join(str(tmp_path), f"edge-{e}.msgpack")
        assert os.path.exists(path)
        template = tree_from_bytes(tree_to_bytes(_vars(0.0)))
        restored = EdgeAggregator.restore(path, template)
        assert restored is not None and restored.edge_id == f"edge-{e}"


def test_edge_crash_drill_end_to_end():
    """tools/chaos_drill.run_edge_crash_drill: the scripted mid-round edge
    kill→restart against a REAL gRPC root — statefile resume, quorum
    close, exact recovered averages, fault recorded by the chaos plan."""
    from fedcrack_tpu.tools.chaos_drill import run_edge_crash_drill

    out = run_edge_crash_drill()
    assert out["fault_fired"]
    assert out["resumed_mid_round"]
    assert out["edge_partial_exact"]
    assert out["root_round_closed"]
    assert out["root_avg_exact"]
    assert out["root_clients"] == ["edge-0", "edge-1"]


def test_grouped_weights_stable_fingerprint(cohort_data, variables, cohort_round_g2):
    """Belt-and-suspenders determinism: two runs of the same grouped round
    produce identical bytes (no hidden RNG/state in the group loop)."""
    mesh, cr = cohort_round_g2
    v1, _ = cr(variables, *cohort_data)
    v2, _ = cr(variables, *cohort_data)
    s1 = hashlib.sha256(tree_to_bytes(jax.device_get(v1))).hexdigest()
    s2 = hashlib.sha256(tree_to_bytes(jax.device_get(v2))).hexdigest()
    assert s1 == s2


def test_tree_rejects_fewer_leaves_than_edges():
    """cohort_size < n_edges is a misconfiguration (some edges would have
    no shard and the root barrier could never close) — a ValueError at
    entry, not an IndexError mid-round (review fix)."""
    with pytest.raises(ValueError, match="cohort_size"):
        run_tree_federation(
            _vars(0.0),
            _make_update,
            n_clients=8,
            cohort_size=2,
            n_rounds=1,
            n_edges=4,
        )


def test_edge_codec_instance_survives_rounds():
    """The edge's upload codec lives for the EDGE's lifetime, like the leaf
    client's: topk_delta's error-feedback residual is cross-round state — a
    per-round codec would drop every round's unsent delta mass forever
    (review fix)."""
    template = tree_from_bytes(tree_to_bytes(_vars(0.0)))
    edge = EdgeAggregator(
        "e", template, update_codec="topk_delta", topk_fraction=0.5
    )
    base = tree_to_bytes(_vars(0.0))
    edge.begin_round(1, base, 0, ["a"])
    edge.offer("a", tree_to_bytes(_vars(1.0)), 10)
    edge.partial()
    first = edge._codec
    assert first is not None
    edge.end_round()
    edge.begin_round(2, base, 1, ["a"])
    edge.offer("a", tree_to_bytes(_vars(2.0)), 10)
    edge.partial()
    assert edge._codec is first  # same instance — residual carried
