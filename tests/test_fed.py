"""Federation logic: serialization round-trip, FedAvg math, state machine."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import fedavg, fedprox_penalty, tree_from_bytes, tree_to_bytes
from fedcrack_tpu.fed import rounds as R


# ---------- serialization ----------

def _tree(seed):
    rng = np.random.default_rng(seed)
    return {
        "layer": {"kernel": rng.normal(size=(3, 4)).astype(np.float32)},
        "bias": rng.normal(size=(4,)).astype(np.float32),
    }


def test_roundtrip_exact():
    t = _tree(0)
    out = tree_from_bytes(tree_to_bytes(t))
    assert np.array_equal(out["layer"]["kernel"], t["layer"]["kernel"])
    assert np.array_equal(out["bias"], t["bias"])


def test_roundtrip_with_template_restores_dtype():
    t = _tree(1)
    blob = tree_to_bytes(t, cast_dtype="bfloat16")
    out = tree_from_bytes(blob, template=t)
    assert out["layer"]["kernel"].dtype == np.float32
    # bf16 wire precision: ~3 decimal digits
    assert np.allclose(out["bias"], t["bias"], atol=0.05)
    # and the wire is half the size
    assert len(blob) < len(tree_to_bytes(t)) * 0.75


def test_leaf_count_mismatch_rejected():
    t = _tree(2)
    with pytest.raises(ValueError, match="leaves"):
        tree_from_bytes(tree_to_bytes({"only": t["bias"]}), template=t)


def test_no_pickle_on_the_wire():
    blob = tree_to_bytes(_tree(3))
    assert not blob.startswith(b"\x80")  # pickle protocol-2+ magic


# ---------- fedavg ----------

def test_fedavg_unweighted_matches_numpy_mean():
    trees = [_tree(i) for i in range(4)]
    avg = fedavg(trees)
    ref = np.mean([t["layer"]["kernel"] for t in trees], axis=0)
    assert np.allclose(avg["layer"]["kernel"], ref, atol=1e-6)


def test_fedavg_weighted_closed_form():
    trees = [_tree(i) for i in range(2)]
    avg = fedavg(trees, weights=[3, 1])
    ref = 0.75 * trees[0]["bias"] + 0.25 * trees[1]["bias"]
    assert np.allclose(avg["bias"], ref, atol=1e-6)


def test_fedavg_rejects_empty_and_bad_weights():
    with pytest.raises(ValueError):
        fedavg([])
    with pytest.raises(ValueError):
        fedavg([_tree(0)], weights=[1, 2])
    with pytest.raises(ValueError):
        fedavg([_tree(0), _tree(1)], weights=[0, 0])


def test_fedprox_penalty_closed_form():
    a = {"w": jnp.ones((2, 2))}
    b = {"w": jnp.zeros((2, 2))}
    assert float(fedprox_penalty(a, b, mu=0.1)) == pytest.approx(0.5 * 0.1 * 4.0)


# ---------- round state machine ----------

CFG = FedConfig(max_rounds=2, cohort_size=2, registration_window_s=10.0)


def boot(cfg=CFG):
    return R.initial_state(cfg, _tree(42))


def enroll_two(state, t0=0.0):
    state, r1 = R.transition(state, R.Ready("a", now=t0))
    assert r1.status == R.SW
    state, r2 = R.transition(state, R.Ready("b", now=t0 + 1))
    assert r2.status == R.SW
    assert r2.config["max_train_round"] == state.config.max_rounds
    assert r2.config["model_type"] == "resunet"
    return state


def done(state, cname, rnd, seed, now, ns=8):
    return R.transition(
        state, R.TrainDone(cname, round=rnd, blob=tree_to_bytes(_tree(seed)), num_samples=ns, now=now)
    )


def test_full_session_two_clients_two_rounds():
    state = enroll_two(boot())
    # round 1: a reports first -> ACY; b completes the round -> ARY + avg blob
    state, ra = done(state, "a", 1, seed=1, now=2.0)
    assert ra.status == R.RESP_ACY
    state, rb = done(state, "b", 1, seed=2, now=3.0)
    assert rb.status == R.RESP_ARY
    avg = tree_from_bytes(rb.blob)
    expect = np.mean([_tree(1)["bias"], _tree(2)["bias"]], axis=0)
    assert np.allclose(avg["bias"], expect, atol=1e-6)  # broadcast == average (fix #1)
    assert state.current_round == 2 and state.model_version == 1
    assert not state.received  # buffer reset (fix #2)
    # round 2 -> FIN
    state, _ = done(state, "a", 2, seed=3, now=4.0)
    state, rfin = done(state, "b", 2, seed=4, now=5.0)
    assert rfin.status == R.FIN
    assert state.phase == R.PHASE_FINISHED
    assert len(state.history) == 2


def test_weighted_aggregation_by_sample_count():
    state = enroll_two(boot())
    state, _ = done(state, "a", 1, seed=1, now=2.0, ns=30)
    state, rb = done(state, "b", 1, seed=2, now=3.0, ns=10)
    avg = tree_from_bytes(rb.blob)
    expect = 0.75 * _tree(1)["bias"] + 0.25 * _tree(2)["bias"]
    assert np.allclose(avg["bias"], expect, atol=1e-6)


def test_late_client_gets_ctw():
    state = enroll_two(boot())
    # 11 s after first ready: window closed on next event
    state, r = R.transition(state, R.Ready("late", now=12.0))
    assert r.status == R.CTW
    assert "late" not in state.cohort


def test_stale_round_rejected_not_crash():
    state = enroll_two(boot())
    state, r = done(state, "a", 99, seed=1, now=2.0)
    assert r.status == R.REJECTED
    assert r.config["reason"] == "stale round"
    state, r = done(state, "stranger", 1, seed=1, now=2.0)
    assert r.status == R.REJECTED


def test_version_poll_wait_then_not_wait():
    state = enroll_two(boot())
    state, r = R.transition(state, R.VersionPoll("a", model_version=0, round=1, now=2.0))
    assert r.status == R.WAIT
    state, _ = done(state, "a", 1, seed=1, now=2.5)
    state, rb = done(state, "b", 1, seed=2, now=3.0)
    state, r = R.transition(state, R.VersionPoll("a", model_version=0, round=1, now=3.5))
    assert r.status == R.NOT_WAIT
    assert np.array_equal(
        tree_from_bytes(r.blob)["bias"], tree_from_bytes(rb.blob)["bias"]
    )


def test_pull_weights_returns_current_global():
    state = enroll_two(boot())
    _, r = R.transition(state, R.PullWeights("a", now=2.0))
    assert np.array_equal(tree_from_bytes(r.blob)["bias"], _tree(42)["bias"])
    # after round 1 the pull must return the average, not the init weights
    state, _ = done(state, "a", 1, seed=1, now=2.0)
    state, _ = done(state, "b", 1, seed=2, now=3.0)
    _, r2 = R.transition(state, R.PullWeights("a", now=4.0))
    assert not np.array_equal(tree_from_bytes(r2.blob)["bias"], _tree(42)["bias"])


def test_deadline_shrinks_cohort():
    cfg = dataclasses.replace(CFG, round_deadline_s=30.0, max_rounds=3)
    state = enroll_two(boot(cfg))
    state, _ = done(state, "a", 1, seed=1, now=2.0)
    # b never reports; deadline passes
    state, _ = R.transition(state, R.Tick(now=50.0))
    assert state.cohort == frozenset({"a"})
    assert state.current_round == 2  # aggregated from a alone
    avg = R.transition(state, R.PullWeights("a", now=51.0))[1]
    assert np.allclose(tree_from_bytes(avg.blob)["bias"], _tree(1)["bias"], atol=1e-6)


def test_log_chunks_accumulate():
    state = enroll_two(boot())
    state, r = R.transition(
        state, R.LogChunk("a", "events.tb", b"abc", now=2.0, offset=0)
    )
    state, r = R.transition(
        state, R.LogChunk("a", "events.tb", b"def", now=2.1, offset=3)
    )
    assert state.logs["a/events.tb"] == b"abcdef"


def test_single_writer_purity_no_shared_mutation():
    """Transitions never mutate the input state (regression for the
    reference's cross-thread mutation bugs, SURVEY.md §2.2(6))."""
    s0 = boot()
    s1, _ = R.transition(s0, R.Ready("a", now=0.0))
    assert s0.cohort == frozenset() and s1.cohort == {"a"}


def test_log_chunk_offsets_idempotent_and_gap_rejected():
    """Retried chunks overwrite themselves (offset-addressed writes), a
    fresh offset=0 upload restarts the buffer, and a gap is rejected."""
    from fedcrack_tpu.configs import FedConfig

    cfg = FedConfig(cohort_size=1)
    state = R.initial_state(cfg, {"params": {"w": np.zeros(2, np.float32)}})
    state, _ = R.transition(state, R.Ready("c", now=0.0))  # uploads need cohort membership
    chunk = lambda data, off: R.LogChunk(
        cname="c", title="t", data=data, now=0.0, offset=off
    )
    state, rep = R.transition(state, chunk(b"abcd", 0))
    assert rep.status == "OK"
    state, _ = R.transition(state, chunk(b"efgh", 4))
    # RPC retry of the second chunk: same bytes, same offset — no duplication
    state, rep = R.transition(state, chunk(b"efgh", 4))
    assert rep.status == "OK" and state.logs["c/t"] == b"abcdefgh"
    # gap (lost chunk) is an explicit rejection, not silent corruption
    _, rep = R.transition(state, chunk(b"zz", 100))
    assert rep.status == R.REJECTED
    # offset=0 restarts the upload (e.g. after a flush or failed attempt)
    state, _ = R.transition(state, chunk(b"new", 0))
    assert state.logs["c/t"] == b"new"
    # drop_log forgets the buffer and is a no-op for unknown keys
    state = R.drop_log(state, "c", "t")
    assert "c/t" not in state.logs
    assert R.drop_log(state, "c", "t").logs == state.logs


def test_silent_cohort_deadline_reopens_enrollment():
    """Fix #5 regression: a deadline with ZERO reports (every cohort member
    died) must re-open enrollment, not stall in PHASE_RUNNING forever."""
    cfg = dataclasses.replace(CFG, round_deadline_s=5.0)
    state = enroll_two(boot(cfg))
    assert state.phase == R.PHASE_RUNNING
    # nobody ever reports; time blows way past the deadline
    state, _ = R.transition(state, R.Tick(now=100.0))
    assert state.phase == R.PHASE_ENROLL
    assert state.cohort == frozenset()
    assert state.current_round == 1       # round counter survives
    assert state.failed_rounds == 1
    # a fresh cohort enrolls and completes the federation from round 1
    state = enroll_two(state, t0=101.0)
    state, _ = done(state, "a", 1, seed=1, now=102.0)
    state, r = done(state, "b", 1, seed=2, now=103.0)
    assert r.status == R.RESP_ARY
    assert state.current_round == 2


def test_silent_cohort_member_can_rejoin_fresh_cohort():
    """A member of a cohort that died wholesale (fix #5 reopen) must be able
    to rejoin even after a FRESH cohort closed enrollment — the dead members
    land in `departed`, so their restart re-admits instead of CTW."""
    cfg = dataclasses.replace(CFG, round_deadline_s=5.0, cohort_size=1)
    state = R.initial_state(cfg, _tree(42))
    state, _ = R.transition(state, R.Ready("a", now=0.0))   # cohort {a}, RUNNING
    state, _ = R.transition(state, R.Tick(now=100.0))       # a died -> reopen
    assert state.phase == R.PHASE_ENROLL and "a" in state.departed
    state, _ = R.transition(state, R.Ready("c", now=101.0))  # fresh cohort closes
    assert state.phase == R.PHASE_RUNNING
    state, r = R.transition(state, R.Ready("a", now=102.0))  # a restarts
    assert r.status == R.SW
    assert state.cohort == frozenset({"a", "c"})


def test_cohort_member_rejoins_after_crash():
    """Fix #6 regression: Ready from an enrolled cname during RUNNING
    re-syncs the client (SW + current round) instead of locking it out."""
    state = enroll_two(boot())
    state, _ = done(state, "a", 1, seed=1, now=2.0)
    # "b" crashes and restarts: its Ready mid-run must re-enroll it
    state, r = R.transition(state, R.Ready("b", now=3.0))
    assert r.status == R.SW
    assert r.config["current_round"] == 1
    assert "b" in state.cohort
    # a true stranger still gets CTW
    _, r = R.transition(state, R.Ready("stranger", now=3.5))
    assert r.status == R.CTW
    # rejoined "b" completes the round
    state, r = done(state, "b", 1, seed=2, now=4.0)
    assert r.status == R.RESP_ARY


def test_rejoin_after_reporting_drops_stale_report():
    """A member that crashed AFTER reporting must not be raced by its own
    stale blob: rejoin drops the pre-crash report so the barrier waits for
    the redo instead of advancing the round underneath the client."""
    state = enroll_two(boot())
    state, _ = done(state, "b", 1, seed=9, now=2.0)   # b reports, then crashes
    state, r = R.transition(state, R.Ready("b", now=3.0))
    assert r.status == R.SW
    assert "b" not in state.received
    # a's report alone must NOT complete the barrier now
    state, r = done(state, "a", 1, seed=1, now=4.0)
    assert r.status == R.RESP_ACY
    # b's fresh report completes the round — no stale-round rejection
    state, r = done(state, "b", 1, seed=2, now=5.0)
    assert r.status == R.RESP_ARY


def test_log_chunk_from_non_cohort_rejected():
    """Only cohort members may fill the in-memory sink — anyone else could
    exhaust the total cap and deny uploads to legitimate clients. This
    includes pre-enrollment senders: an attacker filling the sink before
    the cohort forms would deny every later legitimate upload."""
    state = enroll_two(boot())
    _, r = R.transition(state, R.LogChunk("stranger", "t", b"x", now=2.0))
    assert r.status == R.REJECTED and "not in cohort" in r.title
    s0 = boot()
    _, r = R.transition(s0, R.LogChunk("early", "t", b"x", now=0.0))
    assert r.status == R.REJECTED


def test_departed_member_readmitted_after_deadline_shrink():
    """Fix #6 must hold even when the restart loses the race with the
    deadline: a member shrunk out of the cohort re-admits itself via Ready
    instead of being CTW'd for the rest of the federation."""
    cfg = dataclasses.replace(CFG, round_deadline_s=5.0, max_rounds=3)
    state = enroll_two(boot(cfg))
    state, _ = done(state, "a", 1, seed=1, now=2.0)
    # b misses the deadline: cohort shrinks to {a}, round 1 aggregates
    state, _ = R.transition(state, R.Tick(now=20.0))
    assert state.cohort == frozenset({"a"})
    assert state.departed == frozenset({"b"})
    assert state.current_round == 2
    # b restarts and re-enrolls mid-run -> re-admitted, not CTW
    state, r = R.transition(state, R.Ready("b", now=21.0))
    assert r.status == R.SW
    assert state.cohort == frozenset({"a", "b"})
    assert state.departed == frozenset()
    # round 2 now needs both again
    state, r = done(state, "a", 2, seed=3, now=22.0)
    assert r.status == R.RESP_ACY
    state, r = done(state, "b", 2, seed=4, now=23.0)
    assert r.status == R.RESP_ARY


def test_log_sink_cap_zero_means_uncapped():
    cfg = dataclasses.replace(CFG, log_max_mb_per_upload=0, log_max_mb_total=0)
    state = enroll_two(boot(cfg))
    state, r = R.transition(
        state, R.LogChunk("a", "t", b"x" * (2 * 1024 * 1024), now=2.0)
    )
    assert r.status == "OK"


def test_log_sink_caps_enforced():
    """Fix #7 regression: per-upload and total caps on the in-memory sink."""
    cfg = dataclasses.replace(CFG, log_max_mb_per_upload=1, log_max_mb_total=2)
    state = enroll_two(boot(cfg))
    mib = 1024 * 1024
    # per-upload cap: second MiB+1 byte of one title is rejected
    state, r = R.transition(state, R.LogChunk("a", "big", b"x" * mib, now=2.0))
    assert r.status == "OK"
    _, r = R.transition(
        state, R.LogChunk("a", "big", b"y", now=2.1, offset=mib)
    )
    assert r.status == R.REJECTED and "per-upload cap" in r.title
    # total cap: two 1 MiB titles fill the sink; a third is rejected
    state, r = R.transition(state, R.LogChunk("b", "big", b"x" * mib, now=2.2))
    assert r.status == "OK"
    _, r = R.transition(state, R.LogChunk("a", "more", b"z" * mib, now=2.3))
    assert r.status == R.REJECTED and "total cap" in r.title
    # an over-cap rejection leaves existing buffers intact
    assert len(state.logs["a/big"]) == mib and len(state.logs["b/big"]) == mib


class TestFedOpt:
    """Server-side optimizers on the round pseudo-gradient (FedOpt)."""

    def _vars(self, value):
        return {
            "params": {"w": np.full(3, value, np.float32)},
            "batch_stats": {"bn": {"mean": np.full(3, value, np.float32)}},
        }

    def _session(self, cfg, uploads_per_round):
        """Drive the pure machine: 1-client cohort, given per-round uploads."""
        from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes

        state = R.initial_state(cfg, self._vars(0.0))
        state, _ = R.transition(state, R.Ready(cname="a", now=0.0))
        state, _ = R.transition(
            state, R.Tick(now=cfg.registration_window_s + 1.0)
        )
        blobs = []
        for rnd, up in enumerate(uploads_per_round, start=1):
            state, rep = R.transition(
                state,
                R.TrainDone(
                    cname="a",
                    round=rnd,
                    blob=tree_to_bytes(self._vars(up)),
                    num_samples=4,
                    now=float(rnd),
                ),
            )
            blobs.append(tree_from_bytes(state.global_blob))
        return state, blobs

    def _cfg(self, **kw):
        from fedcrack_tpu.configs import FedConfig

        return FedConfig(
            cohort_size=1, max_rounds=3, registration_window_s=1.0, **kw
        )

    def test_avg_default_is_plain_fedavg(self):
        _, blobs = self._session(self._cfg(), [5.0, 7.0])
        np.testing.assert_allclose(blobs[0]["params"]["w"], 5.0)
        np.testing.assert_allclose(blobs[1]["params"]["w"], 7.0)

    def test_momentum_zero_lr_one_recovers_fedavg(self):
        cfg = self._cfg(
            server_optimizer="momentum", server_lr=1.0, server_momentum=0.0
        )
        _, blobs = self._session(cfg, [5.0, 7.0])
        np.testing.assert_allclose(blobs[0]["params"]["w"], 5.0, rtol=1e-6)
        np.testing.assert_allclose(blobs[1]["params"]["w"], 7.0, rtol=1e-6)

    def test_fedavgm_closed_form(self):
        """optax.sgd trace: m_t = g_t + beta*m_{t-1}, x_t = x_{t-1} - lr*m_t
        with pseudo-gradient g_t = x_{t-1} - avg_t."""
        beta, lr = 0.9, 1.0
        cfg = self._cfg(
            server_optimizer="fedavgm", server_lr=lr, server_momentum=beta
        )
        _, blobs = self._session(cfg, [5.0, 5.0])
        # round 1: x0=0, g1 = 0-5 = -5, m1 = -5, x1 = 0 - (-5) = 5
        np.testing.assert_allclose(blobs[0]["params"]["w"], 5.0, rtol=1e-6)
        # round 2: g2 = 5-5 = 0, m2 = 0 + 0.9*(-5) = -4.5, x2 = 5 + 4.5 = 9.5
        np.testing.assert_allclose(blobs[1]["params"]["w"], 9.5, rtol=1e-6)
        # BN stats NEVER go through the optimizer: plain average each round
        np.testing.assert_allclose(blobs[1]["batch_stats"]["bn"]["mean"], 5.0)

    def test_fedadam_moves_toward_average(self):
        cfg = self._cfg(server_optimizer="fedadam", server_lr=0.1)
        state, blobs = self._session(cfg, [5.0, 5.0])
        w1 = blobs[0]["params"]["w"]
        w2 = blobs[1]["params"]["w"]
        assert np.all(w1 > 0) and np.all(w2 > w1) and np.all(w2 <= 5.01)
        assert state.server_opt_state is not None

    def test_fedadam_closed_form_no_bias_correction(self):
        """Reddi et al.'s FedAdam has NO bias correction: x += lr*m/(sqrt(v)+eps)
        with raw first/second moments. Pins the hand-rolled update against the
        recurrence (optax.adam's bias-corrected step would differ by ~2e-4 in
        round 1 here)."""
        lr, b1, b2, eps = 0.1, 0.9, 0.99, 1e-3
        cfg = self._cfg(server_optimizer="fedadam", server_lr=lr)
        _, blobs = self._session(cfg, [5.0, 5.0])
        x, m, v = 0.0, 0.0, 0.0
        expected = []
        for _ in range(2):
            g = x - 5.0  # pseudo-gradient toward the round average
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            x = x - lr * m / (np.sqrt(v) + eps)
            expected.append(x)
        np.testing.assert_allclose(blobs[0]["params"]["w"], expected[0], rtol=1e-5)
        np.testing.assert_allclose(blobs[1]["params"]["w"], expected[1], rtol=1e-5)

    def test_fedyogi_closed_form(self):
        """Reddi et al.'s FedYogi second moment is additive:
        v_t = v_{t-1} - (1-b2)*sign(v_{t-1} - g^2)*g^2 — pins the update
        against the recurrence over two rounds (which diverges from FedAdam
        at round 2, checked explicitly)."""
        lr, b1, b2, eps = 0.1, 0.9, 0.99, 1e-3
        cfg = self._cfg(server_optimizer="fedyogi", server_lr=lr)
        _, blobs = self._session(cfg, [5.0, 3.0])
        x, m, v = 0.0, 0.0, 0.0
        expected = []
        adam_v = 0.0
        adam_diverges = False
        for avg in (5.0, 3.0):
            g = x - avg
            m = b1 * m + (1 - b1) * g
            adam_v = b2 * adam_v + (1 - b2) * g * g
            v = v - (1 - b2) * np.sign(v - g * g) * g * g
            adam_diverges = adam_diverges or abs(v - adam_v) > 1e-9
            x = x - lr * m / (np.sqrt(v) + eps)
            expected.append(x)
        assert adam_diverges  # the recurrences genuinely differ by round 2
        np.testing.assert_allclose(blobs[0]["params"]["w"], expected[0], rtol=1e-5)
        np.testing.assert_allclose(blobs[1]["params"]["w"], expected[1], rtol=1e-5)

    def test_unknown_kind_rejected(self):
        from fedcrack_tpu.fed.algorithms import make_server_optimizer

        with pytest.raises(ValueError, match="unknown server optimizer"):
            make_server_optimizer("adagrad")


class TestBf16Wire:
    def _state(self):
        cfg = dataclasses.replace(CFG, wire_dtype="bfloat16", cohort_size=2)
        return R.initial_state(cfg, _tree(42))

    def test_broadcast_blob_is_half_size_and_handshake_advertises(self):
        state = self._state()
        assert 0 < len(state.broadcast_blob) < 0.75 * len(state.global_blob)
        state, r = R.transition(state, R.Ready("a", now=0.0))
        assert r.config["wire_dtype"] == "bfloat16"

    def test_round_math_stays_f32_and_broadcast_matches_average(self):
        state = self._state()
        state = enroll_two(state)
        # client uploads arrive bf16-cast (as the handshake instructs)
        blob_a = tree_to_bytes(_tree(1), cast_dtype="bfloat16")
        blob_b = tree_to_bytes(_tree(2), cast_dtype="bfloat16")
        state, _ = R.transition(
            state, R.TrainDone("a", round=1, blob=blob_a, num_samples=8, now=2.0)
        )
        state, rb = R.transition(
            state, R.TrainDone("b", round=1, blob=blob_b, num_samples=8, now=3.0)
        )
        # internal global stays float32 at full precision of the decoded
        # (bf16-rounded) uploads
        internal = tree_from_bytes(state.global_blob)
        assert internal["bias"].dtype == np.float32
        expect = np.mean([_tree(1)["bias"], _tree(2)["bias"]], axis=0)
        np.testing.assert_allclose(internal["bias"], expect, atol=0.05)
        # the reply blob is the bf16 wire copy, decodable via a template
        got = tree_from_bytes(rb.blob, template=_tree(0))
        np.testing.assert_allclose(got["bias"], expect, atol=0.05)
        assert len(rb.blob) < 0.75 * len(state.global_blob)
        # observability reflects the wire size actually broadcast
        assert state.history[-1]["bytes_broadcast"] == len(rb.blob)

    def test_rejects_unknown_wire_dtype(self):
        with pytest.raises(ValueError, match="wire_dtype"):
            dataclasses.replace(CFG, wire_dtype="float16")


# ---------- quorum aggregation (round 8) ----------


class TestQuorum:
    def _cfg(self, **kw):
        return dataclasses.replace(CFG, **kw)

    def test_default_full_barrier_unchanged(self):
        """quorum_fraction=1.0 (default) is the exact pre-quorum barrier."""
        state = enroll_two(boot())
        state, ra = done(state, "a", 1, seed=1, now=2.0)
        assert ra.status == R.RESP_ACY  # 1 of 2 does NOT close the round
        state, rb = done(state, "b", 1, seed=2, now=3.0)
        assert rb.status == R.RESP_ARY

    def test_quorum_closes_early_and_history_records_it(self):
        cfg = self._cfg(cohort_size=4, quorum_fraction=0.5, max_rounds=3)
        state = boot(cfg)
        for i, c in enumerate("abcd"):
            state, _ = R.transition(state, R.Ready(c, now=float(i)))
        assert state.phase == R.PHASE_RUNNING
        state, _ = done(state, "a", 1, seed=1, now=5.0)
        state, r = done(state, "b", 1, seed=2, now=6.0)
        # 2 of 4 = ceil(0.5 * 4): the round closes NOW.
        assert r.status == R.RESP_ARY
        assert state.current_round == 2
        h = state.history[0]
        assert h["quorum"] == 2 and h["cohort_size"] == 4
        assert h["clients"] == ["a", "b"]
        # The cohort is NOT shrunk — the quorum is not a deadline.
        assert state.cohort == frozenset("abcd")

    def test_straggler_resynced_logged_never_averaged(self):
        cfg = self._cfg(cohort_size=2, quorum_fraction=0.5, max_rounds=3)
        state = enroll_two(boot(cfg))
        state, r = done(state, "a", 1, seed=1, now=2.0)
        assert r.status == R.RESP_ARY  # quorum 1-of-2
        # b's round-1 report arrives after the close: resync, not death.
        state, r = done(state, "b", 1, seed=2, now=3.0)
        assert r.status == R.NOT_WAIT
        assert r.config["current_round"] == 2
        assert r.blob  # carries the current weights
        # Round-1 average is a's alone — b's blob never averaged.
        avg = tree_from_bytes(state.global_blob)
        assert np.allclose(avg["bias"], _tree(1)["bias"], atol=1e-6)
        # The stale report is on round 2's record once round 2 closes.
        state, _ = done(state, "b", 2, seed=3, now=4.0)
        assert "b" in state.history[-1]["rejected"]
        assert "stale round" in state.history[-1]["rejected"]["b"]

    def test_future_round_still_rejected(self):
        state = enroll_two(boot(self._cfg(quorum_fraction=0.5)))
        state, r = done(state, "a", 7, seed=1, now=2.0)
        assert r.status == R.REJECTED
        assert r.config["reason"] == "stale round"

    def test_quorum_fraction_validated(self):
        with pytest.raises(ValueError, match="quorum_fraction"):
            self._cfg(quorum_fraction=0.0)
        with pytest.raises(ValueError, match="quorum_fraction"):
            self._cfg(quorum_fraction=1.5)

    def test_deadline_still_backstops_below_quorum(self):
        """Fewer reports than the quorum at the deadline: the shrink still
        fires (quorum never weakens the deadline)."""
        cfg = self._cfg(cohort_size=3, quorum_fraction=2.0 / 3.0,
                        round_deadline_s=10.0, max_rounds=3)
        state = boot(cfg)
        for i, c in enumerate("abc"):
            state, _ = R.transition(state, R.Ready(c, now=float(i)))
        state, _ = done(state, "a", 1, seed=1, now=3.0)
        state, _ = R.transition(state, R.Tick(now=50.0))
        assert state.current_round == 2
        assert state.cohort == frozenset({"a"})
        assert state.departed == frozenset({"b", "c"})


# ---------- update sanitation (round 8) ----------


class TestSanitation:
    def test_nan_update_rejected_and_logged(self):
        state = enroll_two(boot())
        bad = _tree(1)
        bad["bias"] = np.array([np.nan, 1.0, 2.0, 3.0], np.float32)
        state, r = R.transition(
            state,
            R.TrainDone("a", round=1, blob=tree_to_bytes(bad), num_samples=8, now=2.0),
        )
        assert r.status == R.REJECTED
        assert "non-finite" in r.config["reason"]
        assert "a" not in state.received
        assert "non-finite" in state.rejected["a"]

    def test_shape_mismatch_rejected(self):
        state = enroll_two(boot())
        bad = _tree(1)
        bad["bias"] = bad["bias"].reshape(2, 2)  # same size, wrong shape
        state, r = R.transition(
            state,
            R.TrainDone("a", round=1, blob=tree_to_bytes(bad), num_samples=8, now=2.0),
        )
        assert r.status == R.REJECTED and "shape" in r.config["reason"]

    def test_truncated_and_garbage_rejected(self):
        state = enroll_two(boot())
        good = tree_to_bytes(_tree(1))
        for blob in (good[: len(good) // 2], b"\x00\xff garbage"):
            state, r = R.transition(
                state, R.TrainDone("a", round=1, blob=blob, num_samples=8, now=2.0)
            )
            assert r.status == R.REJECTED
            assert "update rejected" in r.config["reason"]

    def test_negative_sample_count_rejected(self):
        state = enroll_two(boot())
        state, r = R.transition(
            state,
            R.TrainDone("a", round=1, blob=tree_to_bytes(_tree(1)), num_samples=-4, now=2.0),
        )
        assert r.status == R.REJECTED and "negative" in r.config["reason"]

    def test_rejection_lands_in_history_and_round_still_completes(self):
        state = enroll_two(boot())
        bad = _tree(1)
        bad["bias"] = np.full(4, np.inf, np.float32)
        state, _ = R.transition(
            state,
            R.TrainDone("a", round=1, blob=tree_to_bytes(bad), num_samples=8, now=2.0),
        )
        # a retries with a clean update; b reports; the round closes clean.
        state, _ = done(state, "a", 1, seed=1, now=3.0)
        state, r = done(state, "b", 1, seed=2, now=4.0)
        assert r.status == R.RESP_ARY
        h = state.history[0]
        assert h["clients"] == ["a", "b"]
        assert "non-finite" in h["rejected"]["a"]
        assert state.rejected == {}  # per-round map reset after aggregation

    def test_bf16_wire_passes_sanitation(self):
        cfg = dataclasses.replace(CFG, wire_dtype="bfloat16")
        state = enroll_two(R.initial_state(cfg, _tree(42)))
        blob = tree_to_bytes(_tree(1), cast_dtype="bfloat16")
        state, r = R.transition(
            state, R.TrainDone("a", round=1, blob=blob, num_samples=8, now=2.0)
        )
        assert r.status == R.RESP_ACY  # dtype is not the contract; shape is

    def test_sanitation_can_be_disabled(self):
        cfg = dataclasses.replace(CFG, sanitize_updates=False)
        state = enroll_two(R.initial_state(cfg, _tree(42)))
        bad = _tree(1)
        bad["bias"] = np.full(4, np.nan, np.float32)
        state, r = R.transition(
            state,
            R.TrainDone("a", round=1, blob=tree_to_bytes(bad), num_samples=8, now=2.0),
        )
        assert r.status == R.RESP_ACY  # explicit opt-out admits it


# ---------- deadline boundary (round-8 satellite: >= vs > unified) ----------


def test_deadline_fires_exactly_at_boundary():
    """Both time windows close AT the boundary instant: enrollment already
    used >=, the round deadline previously used > — one tick landing exactly
    on round_start + deadline must fire the shrink."""
    cfg = dataclasses.replace(CFG, round_deadline_s=30.0, max_rounds=3,
                              registration_window_s=10.0)
    state = boot(cfg)
    state, _ = R.transition(state, R.Ready("a", now=0.0))
    state, _ = R.transition(state, R.Ready("b", now=0.0))  # closes at now=0.0
    assert state.phase == R.PHASE_RUNNING and state.round_started_at == 0.0
    state, _ = done(state, "a", 1, seed=1, now=1.0)
    # Exactly AT the deadline: must fire (was: fired only strictly past it).
    state, _ = R.transition(state, R.Tick(now=30.0))
    assert state.current_round == 2
    assert state.cohort == frozenset({"a"})
    # Symmetry pin: enrollment window also closes exactly at the boundary.
    s2 = boot(cfg)
    s2, _ = R.transition(s2, R.Ready("a", now=0.0))
    s2, _ = R.transition(s2, R.Tick(now=10.0))
    assert s2.phase == R.PHASE_RUNNING


def test_restored_enroll_state_rearms_window():
    """A statefile-restored ENROLL state with a partial cohort must not sit
    open forever: enroll_opened_at is None after restore (dead process's
    clock), and already-enrolled clients never re-send Ready — the window
    re-arms from the first post-restart event and then closes normally
    (review finding: previously only round_started_at re-armed)."""
    cfg = dataclasses.replace(CFG, cohort_size=3, registration_window_s=10.0)
    state = boot(cfg)
    state, _ = R.transition(state, R.Ready("a", now=0.0))  # partial cohort
    restored = state._replace(enroll_opened_at=None, round_started_at=None)
    # First post-restart event re-arms the window...
    restored, _ = R.transition(restored, R.Tick(now=500.0))
    assert restored.phase == R.PHASE_ENROLL
    assert restored.enroll_opened_at == 500.0
    # ...which then closes on schedule and the federation proceeds.
    restored, _ = R.transition(restored, R.Tick(now=510.0))
    assert restored.phase == R.PHASE_RUNNING
    assert restored.cohort == frozenset({"a"})


def test_restored_running_state_rearms_deadline():
    """A statefile-restored RUNNING state has no round_started_at (the dead
    process's clock is meaningless): the first event re-arms it, and the
    deadline counts from there."""
    cfg = dataclasses.replace(CFG, round_deadline_s=10.0, max_rounds=3)
    state = enroll_two(boot(cfg))
    state, _ = done(state, "a", 1, seed=1, now=2.0)
    restored = state._replace(round_started_at=None, enroll_opened_at=None)
    # First post-restart event at t=1000: re-arms, does NOT instantly fire.
    restored, _ = R.transition(restored, R.Tick(now=1000.0))
    assert restored.phase == R.PHASE_RUNNING
    assert restored.round_started_at == 1000.0
    assert restored.current_round == 1
    # ... and the deadline then fires 10 s later as usual.
    restored, _ = R.transition(restored, R.Tick(now=1010.0))
    assert restored.current_round == 2


# ---------- state-machine property test (round-8 satellite) ----------


class TestTransitionProperties:
    """Randomized interleavings from a seed: the liveness invariant (no
    reachable RUNNING state survives deadline ticks without progress) and
    structural invariants (gapless history, received ⊆ cohort, round
    counter == |history| + 1) hold along EVERY path."""

    CLIENTS = ["a", "b", "c", "d"]

    def _random_event(self, rng, state, now):
        c = rng.choice(self.CLIENTS)
        kind = rng.randrange(7)
        if kind == 0:
            return R.Ready(c, now=now)
        if kind == 1:
            return R.PullWeights(c, now=now)
        if kind == 2:
            return R.TrainingNotice(c, now=now)
        if kind == 3:
            return R.LogChunk(c, "t", b"x" * rng.randrange(1, 64), now=now)
        if kind == 4:
            return R.VersionPoll(
                c, model_version=rng.randrange(4), round=rng.randrange(1, 5), now=now
            )
        if kind == 5:
            return R.Tick(now=now)
        # TrainDone: mostly-valid round, sometimes-poisoned payload
        rnd = state.current_round if rng.random() < 0.7 else rng.randrange(1, 6)
        if rng.random() < 0.25:
            blob = b"garbage" if rng.random() < 0.5 else tree_to_bytes(
                {"bias": np.full(4, np.nan, np.float32)}
            )
        else:
            blob = tree_to_bytes(_tree(rng.randrange(100)))
        return R.TrainDone(c, round=rnd, blob=blob, num_samples=rng.choice([0, 4, 8]), now=now)

    def _check_invariants(self, state):
        assert set(state.received) <= set(state.cohort)
        rounds = [h["round"] for h in state.history]
        assert rounds == list(range(1, len(rounds) + 1)), f"gapped: {rounds}"
        assert state.current_round == len(state.history) + 1
        assert not (set(state.cohort) & set(state.departed))
        if state.phase == R.PHASE_FINISHED:
            assert state.current_round > state.config.max_rounds

    @pytest.mark.parametrize("seed", range(8))
    def test_random_interleavings_liveness_and_gapless_history(self, seed):
        import random as _random

        rng = _random.Random(seed)
        cfg = dataclasses.replace(
            CFG,
            max_rounds=3,
            cohort_size=rng.choice([2, 3]),
            registration_window_s=5.0,
            round_deadline_s=10.0,
            quorum_fraction=rng.choice([1.0, 0.5, 2.0 / 3.0]),
        )
        state = boot(cfg)
        now = 0.0
        for _ in range(150):
            now += rng.uniform(0.0, 2.0)
            state, reply = R.transition(state, self._random_event(rng, state, now))
            assert isinstance(reply, R.Reply) and reply.status
            self._check_invariants(state)

        # Liveness drain: with only Ticks past the deadline, every RUNNING
        # state must make progress (aggregate, reopen, or finish) — the
        # machine can never sit in RUNNING forever on an empty event queue.
        for _ in range(2 * cfg.max_rounds + 4):
            if state.phase != R.PHASE_RUNNING:
                break
            before = (state.current_round, state.phase, state.failed_rounds)
            now += cfg.round_deadline_s + 1.0
            state, _ = R.transition(state, R.Tick(now=now))
            self._check_invariants(state)
            after = (state.current_round, state.phase, state.failed_rounds)
            assert after != before, f"seed {seed}: deadline tick made no progress"
        assert state.phase in (R.PHASE_ENROLL, R.PHASE_FINISHED), (
            f"seed {seed}: still RUNNING after the drain"
        )


# ---------- seeded cohort sampling (round 13) ----------


class TestCohortSampling:
    """fed.algorithms.sample_cohort: the determinism/validity/coverage
    properties the cohort-scale trajectory-reproducibility claim rests on."""

    def test_same_seed_same_multi_round_sequence(self):
        from fedcrack_tpu.fed.algorithms import sample_cohort

        seq_a = [sample_cohort(500, 64, r, seed=42) for r in range(20)]
        seq_b = [sample_cohort(500, 64, r, seed=42) for r in range(20)]
        for a, b in zip(seq_a, seq_b):
            np.testing.assert_array_equal(a, b)
        # Pure function of (seed, round): drawing rounds out of order or
        # skipping rounds changes nothing (no hidden RNG state advances).
        np.testing.assert_array_equal(
            sample_cohort(500, 64, 17, seed=42), seq_a[17]
        )

    def test_cohorts_are_valid_subsets(self):
        from fedcrack_tpu.fed.algorithms import sample_cohort

        for r in range(50):
            c = sample_cohort(200, 33, r, seed=7)
            assert c.shape == (33,)
            assert len(set(c.tolist())) == 33  # without replacement
            assert c.min() >= 0 and c.max() < 200
            assert np.all(np.diff(c) > 0)  # sorted

    def test_different_seeds_and_rounds_differ(self):
        from fedcrack_tpu.fed.algorithms import sample_cohort

        a = sample_cohort(1000, 100, 0, seed=1)
        b = sample_cohort(1000, 100, 1, seed=1)
        c = sample_cohort(1000, 100, 0, seed=2)
        assert not np.array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_long_run_coverage_hits_every_client(self):
        from fedcrack_tpu.fed.algorithms import sample_cohort

        n, k = 128, 16
        seen: set = set()
        for r in range(200):
            seen.update(sample_cohort(n, k, r, seed=3).tolist())
            if len(seen) == n:
                break
        assert len(seen) == n, f"only {len(seen)}/{n} clients ever sampled"

    def test_full_population_cohort_is_identity(self):
        from fedcrack_tpu.fed.algorithms import sample_cohort

        np.testing.assert_array_equal(
            sample_cohort(10, 10, 5, seed=0), np.arange(10)
        )

    def test_validation(self):
        from fedcrack_tpu.fed.algorithms import sample_cohort

        with pytest.raises(ValueError, match="n_clients"):
            sample_cohort(0, 1, 0)
        with pytest.raises(ValueError, match="cohort_size"):
            sample_cohort(10, 0, 0)
        with pytest.raises(ValueError, match="cohort_size"):
            sample_cohort(10, 11, 0)

    def test_fedconfig_cohort_seed_round_trips(self):
        cfg = FedConfig(cohort_seed=99)
        assert FedConfig.from_json(cfg.to_json()).cohort_seed == 99
        with pytest.raises(ValueError, match="cohort_seed"):
            FedConfig(cohort_seed=-1)
