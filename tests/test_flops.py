"""The analytic FLOPs model must agree with XLA's own HLO cost analysis —
otherwise every MFU number built on it is fiction."""

import jax
import jax.numpy as jnp
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models import ResUNet
from fedcrack_tpu.obs.flops import (
    TRAIN_STEP_FLOPS_MULTIPLIER,
    device_peak_flops,
    mfu,
    resunet_forward_flops,
    train_step_flops,
)


def test_forward_flops_match_xla_cost_analysis():
    # Flagship shape (convs dominate; at tiny shapes XLA's accounting of
    # padding/transpose-conv edges diverges more).
    cfg = ModelConfig()
    model = ResUNet(config=cfg)
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, *cfg.input_shape)), train=False
    )
    batch = 4
    images = jnp.zeros((batch, *cfg.input_shape))

    def fwd(v, x):
        return model.apply(v, x, train=False)

    analysis = jax.jit(fwd).lower(variables, images).compile().cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0]
    xla_flops = float(analysis["flops"])
    analytic = resunet_forward_flops(cfg, batch)
    assert 0.75 * xla_flops <= analytic <= 1.25 * xla_flops, (
        f"analytic {analytic:.3e} vs XLA {xla_flops:.3e}"
    )


def test_flops_scale_with_resolution_and_batch():
    f128 = resunet_forward_flops(ModelConfig(img_size=128))
    f256 = resunet_forward_flops(ModelConfig(img_size=256))
    # Fully convolutional: 4x the pixels is 4x the FLOPs, exactly.
    assert f256 == pytest.approx(4.0 * f128)
    assert resunet_forward_flops(ModelConfig(), batch_size=16) == pytest.approx(
        16.0 * f128
    )


def test_train_step_is_forward_times_multiplier():
    cfg = ModelConfig(img_size=32)
    assert train_step_flops(cfg, 8) == pytest.approx(
        TRAIN_STEP_FLOPS_MULTIPLIER * resunet_forward_flops(cfg, 8)
    )


def test_flops_are_canonical_across_layouts():
    """MFU-honesty invariant (round 6): the layout transforms re-express the
    same math with zero-extended kernels, and the FLOPs model must charge
    every layout the REFERENCE topology — an A/B whose transformed variant
    got billed its structural-zero MACs would report inflated MFU."""
    for img in (32, 128):
        ref = train_step_flops(ModelConfig(img_size=img), 4)
        for stem, res in (
            ("s2d", "reference"),
            ("s2d_full", "reference"),
            ("reference", "packed"),
            ("s2d", "packed"),
        ):
            cfg = ModelConfig(img_size=img, stem_layout=stem, res_layout=res)
            assert train_step_flops(cfg, 4) == ref


def test_peak_flops_env_override_and_unknown_kind(monkeypatch):
    monkeypatch.setenv("FEDCRACK_PEAK_TFLOPS", "197")
    assert device_peak_flops() == pytest.approx(197e12)
    assert mfu(step_time_s=0.010, flops_per_step=197e12 * 0.010 * 0.5) == pytest.approx(
        0.5
    )
    monkeypatch.delenv("FEDCRACK_PEAK_TFLOPS")
    # The CPU test backend has no known MXU peak: MFU must be None, not a lie.
    assert device_peak_flops() is None
    assert mfu(0.010, 1e9) is None
