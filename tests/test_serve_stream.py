"""Frame-coherent video serving (round 19): the per-stream tile cache, the
crack tracker, stream chaos, and the StreamPredict gRPC front door.

The load-bearing claim, pinned from four directions here:

- **byte identity**: a cached session's per-frame probs equal
  ``engine.predict_tiled`` bit-for-bit at every motion fraction (0, 0.1,
  0.5, 1.0 — all-hits through all-misses), across a cache reset, with the
  cache disabled, under an LRU bound, and for the frame that straddles a
  live hot swap (the version-in-key invalidation);
- **accounting**: static frames compute zero tiles, full-noise frames
  compute all of them, a swap/reset frame is a clean full re-run;
- **tracker**: contour ids are stable under slow motion, growth is
  monotone on a growing blob, and unseen tracks retire after ``miss_ttl``;
- **front door**: load_gen's ``--profile video`` drives open/frames/close
  over the real socket with the wire-level stateless audit green, and
  malformed opens are rejected 1:1 without killing the session RPC.
"""

import json
import queue
import threading

import numpy as np
import pytest

pytestmark = pytest.mark.serve

TINY_KW = dict(
    img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
BUCKETS = (16, 32)
FRAME = 64


@pytest.fixture(scope="module")
def stack():
    """One compiled engine + two weight versions shared by the module."""
    import jax

    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import InferenceEngine

    model_config = ModelConfig(**TINY_KW)
    serve_config = ServeConfig(
        bucket_sizes=BUCKETS, max_batch=4, max_delay_ms=10.0, tile_overlap=4
    )
    engine = InferenceEngine(model_config, serve_config)
    var0 = init_variables(jax.random.key(0), model_config)
    var1 = init_variables(jax.random.key(1), model_config)
    return engine, var0, var1


class _Static:
    """Weights source pinned to one version (the no-swap arm)."""

    def __init__(self, version, variables):
        self._snap = (version, variables)

    def snapshot(self):
        return self._snap


class _SwapAfter:
    """Weights source that installs v1 immediately AFTER handing out v0 for
    the ``at``-th snapshot — the swap lands while that frame computes, so
    the frame itself must stay entirely on v0 (one snapshot per frame) and
    the NEXT frame must be a full re-run on v1."""

    def __init__(self, var0, var1, at):
        self.var0, self.var1, self.at = var0, var1, at
        self.calls = 0

    def snapshot(self):
        self.calls += 1
        if self.calls <= self.at:
            return 0, self.var0
        return 1, self.var1


def _frames(n, motion_fraction, seed=0, size=FRAME):
    from fedcrack_tpu.tools.load_gen import make_frame_sequence

    return make_frame_sequence(n, size, motion_fraction, seed=seed)


# ---- the tentpole contract: cached == stateless, byte for byte ----


@pytest.mark.parametrize("motion", [0.0, 0.1, 0.5, 1.0])
def test_motion_sweep_byte_identity(stack, motion):
    """Seeded property sweep over the motion fraction: whatever mix of
    cached and computed tiles serves a frame, the bytes equal stateless
    ``predict_tiled`` — and the cache accounting matches the geometry at
    the extremes (0.0 = all hits after frame 0, 1.0 = never a hit)."""
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    # 128 px over 32 px tiles (5 tile rows): at 64 px a mid-fraction moving
    # band can straddle ALL 3 tile rows and the accounting claim vanishes.
    size = 2 * FRAME
    session = StreamSession(engine, _Static(0, var0), height=size, width=size)
    frames = _frames(6, motion, seed=int(motion * 10), size=size)
    steady = []
    for i, frame in enumerate(frames):
        result = session.process_frame(frame)
        assert result.probs.tobytes() == np.asarray(
            engine.predict_tiled(var0, frame)
        ).tobytes(), f"motion={motion} frame={i}"
        if i == 0:
            assert result.full_rerun and result.cache_hits == 0
        else:
            steady.append(result)
        if i > 0 and motion == 0.0:
            assert result.tiles_computed == 0
            assert result.cache_hits == result.tiles_total
        if i > 0 and motion == 1.0:
            # Every row rewritten with fresh noise: no tile survives.
            assert result.tiles_computed == result.tiles_total
    if 0.0 < motion < 1.0:
        computed = sum(r.tiles_computed for r in steady)
        total = sum(r.tiles_total for r in steady)
        assert 0 < computed < total, f"motion={motion}: {computed}/{total}"


def test_frame_straddling_hot_swap_byte_identity(stack):
    """The swap lands while frame ``at-1`` is computing: that frame answers
    entirely from v0 (the one-snapshot barrier), the next frame pins v1,
    finds every cached key unreachable (version is IN the key), purges the
    stale entries, and full-re-runs to bytes identical to stateless v1."""
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, var1 = stack
    at = 3
    session = StreamSession(
        engine, _SwapAfter(var0, var1, at), height=FRAME, width=FRAME
    )
    frames = _frames(5, 0.1, seed=42)
    for i, frame in enumerate(frames):
        result = session.process_frame(frame)
        want_vars = var0 if i < at else var1
        assert result.model_version == (0 if i < at else 1)
        assert result.probs.tobytes() == np.asarray(
            engine.predict_tiled(want_vars, frame)
        ).tobytes(), f"frame={i}"
        if i == at:
            assert result.full_rerun and result.cache_hits == 0
            assert result.evicted > 0  # v0 entries purged, not served


def test_static_sequence_computes_zero_tiles_after_first(stack):
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(engine, _Static(0, var0), height=FRAME, width=FRAME)
    frame = _frames(1, 0.0)[0]
    first = session.process_frame(frame)
    assert first.tiles_computed == first.tiles_total
    for _ in range(3):
        again = session.process_frame(frame)
        assert again.tiles_computed == 0
        assert again.probs.tobytes() == first.probs.tobytes()


def test_reset_forces_full_rerun_same_bytes(stack):
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(engine, _Static(0, var0), height=FRAME, width=FRAME)
    frame = _frames(1, 0.0, seed=5)[0]
    before = session.process_frame(frame)
    assert session.process_frame(frame).tiles_computed == 0
    session.reset()
    assert session.cache_len() == 0
    after = session.process_frame(frame)
    assert after.full_rerun and after.tiles_computed == after.tiles_total
    assert after.probs.tobytes() == before.probs.tobytes()


def test_cache_disabled_escape_hatch(stack):
    """cache_tiles=0 is the full re-run escape hatch: nothing is ever
    cached, every frame recomputes everything, bytes unchanged."""
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(
        engine, _Static(0, var0), height=FRAME, width=FRAME, cache_tiles=0
    )
    for frame in _frames(3, 0.0, seed=6):
        result = session.process_frame(frame)
        assert result.full_rerun
        assert result.tiles_computed == result.tiles_total
        assert session.cache_len() == 0
        assert result.probs.tobytes() == np.asarray(
            engine.predict_tiled(var0, frame)
        ).tobytes()


def test_lru_bound_evicts_but_never_changes_bytes(stack):
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(
        engine, _Static(0, var0), height=FRAME, width=FRAME, cache_tiles=3
    )
    evicted = 0
    for frame in _frames(4, 0.5, seed=7):
        result = session.process_frame(frame)
        evicted += result.evicted
        assert session.cache_len() <= 3
        assert result.probs.tobytes() == np.asarray(
            engine.predict_tiled(var0, frame)
        ).tobytes()
    assert evicted > 0


def test_undersized_frame_pads_like_predict_tiled(stack):
    """A session smaller than the largest bucket takes the same zero-pad
    route as predict_tiled — identity must hold there too."""
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(engine, _Static(0, var0), height=24, width=24)
    rng = np.random.default_rng(8)
    for _ in range(2):
        frame = rng.integers(0, 256, (24, 24, 3), dtype=np.uint8)
        result = session.process_frame(frame)
        assert result.probs.shape == (24, 24, 1)
        assert result.probs.tobytes() == np.asarray(
            engine.predict_tiled(var0, frame)
        ).tobytes()


def test_session_input_validation(stack):
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(engine, _Static(0, var0), height=FRAME, width=FRAME)
    with pytest.raises(ValueError, match="frame shape"):
        session.process_frame(np.zeros((32, 64, 3), np.uint8))
    with pytest.raises(ValueError, match="channels"):
        session.process_frame(np.zeros((FRAME, FRAME, 1), np.uint8))
    with pytest.raises(ValueError, match="uint8"):
        session.process_frame(np.zeros((FRAME, FRAME, 3), np.float32))


# ---- temporal layer: EMA smoothing + crack tracking ----


def test_smoothing_never_touches_the_raw_contract(stack):
    """EMA probs are a separate output; result.probs stays stateless-
    identical with smoothing on."""
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(
        engine, _Static(0, var0), height=FRAME, width=FRAME, smooth_alpha=0.7
    )
    frames = _frames(3, 0.1, seed=9)
    for frame in frames:
        result = session.process_frame(frame)
        assert result.smoothed is not None
        assert result.smoothed.shape == result.probs.shape
        assert result.probs.tobytes() == np.asarray(
            engine.predict_tiled(var0, frame)
        ).tobytes()


def _blob_mask(size, cx, cy, r):
    yy, xx = np.mgrid[0:size, 0:size]
    return (((yy - cy) ** 2 + (xx - cx) ** 2) <= r * r).astype(np.uint8) * 255


def test_tracker_stable_ids_and_growth():
    """A blob drifting 2 px/frame and growing keeps ONE track id, its
    area_growth_px is positive, and a vanished blob retires after
    miss_ttl frames."""
    from fedcrack_tpu.serve.stream import CrackTracker

    tracker = CrackTracker(match_dist=8.0, miss_ttl=2)
    ids = set()
    last = None
    for t in range(4):
        tracks = tracker.update(_blob_mask(64, 20 + 2 * t, 20, 5 + t), t)
        assert len(tracks) == 1
        ids.add(tracks[0]["id"])
        last = tracks[0]
    assert len(ids) == 1
    assert last["area_growth_px"] > 0
    # Blob disappears: the track survives miss_ttl-1 empty frames, then
    # retires.
    empty = np.zeros((64, 64), np.uint8)
    tracker.update(empty, 4)
    assert any(t["id"] in ids for t in tracker.snapshot())
    tracker.update(empty, 5)
    assert not any(t["id"] in ids for t in tracker.snapshot())


def test_tracker_new_blob_gets_new_id():
    from fedcrack_tpu.serve.stream import CrackTracker

    tracker = CrackTracker(match_dist=5.0)
    first = tracker.update(_blob_mask(64, 16, 16, 4), 0)
    both = tracker.update(
        np.maximum(_blob_mask(64, 16, 16, 4), _blob_mask(64, 48, 48, 4)), 1
    )
    assert len(first) == 1 and len(both) == 2
    assert len({t["id"] for t in both}) == 2
    assert first[0]["id"] in {t["id"] for t in both}


def test_tracker_validation_and_json():
    from fedcrack_tpu.serve.stream import CrackTracker, tracks_to_json

    with pytest.raises(ValueError, match="match_dist"):
        CrackTracker(match_dist=0.0)
    with pytest.raises(ValueError, match="miss_ttl"):
        CrackTracker(match_dist=1.0, miss_ttl=0)
    tracker = CrackTracker(match_dist=5.0)
    tracks = tracker.update(_blob_mask(32, 10, 10, 3), 0)
    parsed = json.loads(tracks_to_json(tracks))
    assert parsed == json.loads(tracks_to_json(tracks))  # deterministic
    assert parsed[0]["id"] == tracks[0]["id"]


def test_session_tracking_through_frames(stack):
    from fedcrack_tpu.serve.stream import StreamSession

    engine, var0, _ = stack
    session = StreamSession(
        engine, _Static(0, var0), height=FRAME, width=FRAME, track=True
    )
    result = session.process_frame(_frames(1, 0.0, seed=11)[0])
    assert isinstance(result.tracks, list)


# ---- chaos: the SERVE_STREAM_RESET fault ----


def test_chaos_stream_reset_fires_once_and_keeps_bytes(stack):
    from fedcrack_tpu.chaos.inject import StreamChaos
    from fedcrack_tpu.chaos.plan import SERVE_STREAM_RESET, Fault, FaultPlan
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.serve.stream import StreamSession, StreamSessionManager

    engine, var0, _ = stack
    registry = MetricsRegistry()
    manager = StreamSessionManager(engine, _Static(0, var0), registry=registry)
    plan = FaultPlan([Fault(kind=SERVE_STREAM_RESET, round=2)])
    manager.chaos = StreamChaos(plan, manager=manager)
    session = StreamSession(
        engine,
        _Static(0, var0),
        height=FRAME,
        width=FRAME,
        chaos=manager.chaos,
    )
    frame = _frames(1, 0.0, seed=12)[0]
    results = [session.process_frame(frame) for _ in range(4)]
    assert [r.full_rerun for r in results] == [True, False, True, False]
    assert len(plan.triggered) == 1
    assert sum(registry.values()["serve_stream_resets_total"].values()) == 1
    assert all(r.probs.tobytes() == results[0].probs.tobytes() for r in results)


def test_chaos_plan_generates_stream_kind():
    from fedcrack_tpu.chaos.plan import SERVE_STREAM_RESET, FaultPlan

    plan = FaultPlan.generate(
        3, n_rounds=6, clients=(), kinds=(SERVE_STREAM_RESET,), n_faults=4
    )
    assert all(f.kind == SERVE_STREAM_RESET for f in plan.pending)
    assert all(0 <= f.round < 6 for f in plan.pending)


# ---- the session manager: bounds + serve_stream_* metrics ----


def test_manager_bounds_and_metrics_exposition(stack):
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.serve.stream import StreamSessionManager

    engine, var0, _ = stack
    registry = MetricsRegistry()
    manager = StreamSessionManager(
        engine, _Static(0, var0), max_sessions=2, registry=registry
    )
    session = manager.open("a", height=FRAME, width=FRAME)
    manager.open("b", height=FRAME, width=FRAME)
    with pytest.raises(ValueError, match="already open"):
        manager.open("a", height=FRAME, width=FRAME)
    with pytest.raises(ValueError, match="bound"):
        manager.open("c", height=FRAME, width=FRAME)
    assert manager.open_sessions() == 2
    assert manager.close("b") is not None
    assert manager.close("b") is None
    assert manager.get("a") is session

    for frame in _frames(2, 0.0, seed=13):
        manager.record(session.process_frame(frame))
    stats = manager.stats()
    assert stats["tiles_total"] > 0
    assert stats["hit_ratio"] > 0
    assert stats["effective_speedup"] > 1.0
    expo = registry.exposition()
    for name in (
        "serve_stream_sessions_total",
        "serve_stream_frames_total",
        "serve_stream_cache_hits_total",
        "serve_stream_cache_misses_total",
        "serve_stream_cache_evictions_total",
        "serve_stream_full_rerun_total",
        "serve_stream_resets_total",
        "serve_stream_frame_seconds",
        "serve_stream_cache_hit_ratio",
        "serve_stream_effective_speedup_ratio",
    ):
        assert name in expo, name


def test_stream_config_validation():
    from fedcrack_tpu.configs import ServeConfig

    with pytest.raises(ValueError, match="stream_cache_tiles"):
        ServeConfig(stream_cache_tiles=-1)
    with pytest.raises(ValueError, match="stream_max_sessions"):
        ServeConfig(stream_max_sessions=0)
    with pytest.raises(ValueError, match="stream_track_match_frac"):
        ServeConfig(stream_track_match_frac=0.0)


# ---- the gRPC front door ----


@pytest.fixture(scope="module")
def grpc_stack(stack):
    from fedcrack_tpu.serve import (
        MicroBatcher,
        ModelVersionManager,
        ServeServer,
        ServeServerThread,
        ServeService,
    )
    from fedcrack_tpu.serve.stream import StreamSessionManager

    engine, var0, _ = stack
    mgr = ModelVersionManager(engine, var0)
    batcher = MicroBatcher(engine, mgr, max_delay_ms=5.0)
    stream_manager = StreamSessionManager(engine, mgr, max_sessions=4)
    server = ServeServer(
        ServeService(engine, batcher, mgr, stream_manager=stream_manager),
        port=0,
    )
    with ServeServerThread(server) as thread:
        yield thread.port, mgr, stream_manager
    batcher.close()
    mgr.stop()


def test_front_door_video_profile_end_to_end(grpc_stack):
    """load_gen --profile video over the real socket: mixed still + video
    traffic, zero drops, and the wire-level stateless byte audit green."""
    from fedcrack_tpu.tools.load_gen import run_load

    port, _, _ = grpc_stack
    summary = run_load(
        f"127.0.0.1:{port}",
        profile="video",
        n_requests=4,
        concurrency=2,
        sizes=(32,),
        seed=0,
        streams=2,
        frames_per_stream=5,
        motion_fraction=0.1,
        video_size=FRAME,
        audit_every=2,
    )
    assert summary["mode"] == "video"
    assert summary["completed"] == 4 and summary["dropped"] == 0
    video = summary["video"]
    assert video["frames_completed"] == 10 and video["dropped"] == 0
    assert video["open_failed"] == 0
    assert video["audit"]["checked"] > 0 and video["audit"]["ok"]
    assert video["hit_ratio"] > 0
    assert video["effective_speedup"] > 1.0


def test_front_door_rejects_bad_opens_without_killing_rpc(grpc_stack):
    """One response per message even on rejection: bad channels and a
    duplicate open are REJECTED, the stream stays usable, and close acks."""
    import grpc

    from fedcrack_tpu.tools.load_gen import _frame_chunks, _video_call, pb

    port, _, _ = grpc_stack
    frame = np.zeros((FRAME, FRAME, 3), np.uint8)
    msgs = [
        pb.StreamRequest(
            stream_id="t",
            open=pb.StreamOpen(height=FRAME, width=FRAME, channels=2),
        ),
        pb.StreamRequest(
            stream_id="t", open=pb.StreamOpen(height=FRAME, width=FRAME)
        ),
        pb.StreamRequest(
            stream_id="t", open=pb.StreamOpen(height=FRAME, width=FRAME)
        ),
        *_frame_chunks("t", 0, frame, chunk_bytes=1 << 20, crc=True),
        pb.StreamRequest(stream_id="ghost", frame=pb.StreamFrame(frame_id=9)),
        pb.StreamRequest(stream_id="t", close=pb.StreamClose()),
    ]
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        got = list(_video_call(channel)(iter(msgs)))
    finally:
        channel.close()
    assert [r.status for r in got] == [
        "REJECTED",  # channels=2
        "OK",        # open
        "REJECTED",  # duplicate open on the same call
        "OK",        # the frame
        "REJECTED",  # frame for a never-opened stream
        "OK",        # close
    ]
    assert got[1].title == "OPENED" and got[-1].title == "CLOSED"
    assert got[3].full_rerun and got[3].tiles_computed == got[3].tiles_total
    assert len(got[3].mask) == FRAME * FRAME


def test_front_door_session_slots_released_when_rpc_ends(grpc_stack):
    """A dropped connection cannot leak sessions toward the bound."""
    import grpc

    from fedcrack_tpu.tools.load_gen import _video_call, pb

    port, _, stream_manager = grpc_stack
    before = stream_manager.open_sessions()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        call = _video_call(channel)
        q: "queue.Queue" = queue.Queue()

        def gen():
            while True:
                item = q.get()
                if item is None:
                    return
                yield item

        q.put(
            pb.StreamRequest(
                stream_id="leaky",
                open=pb.StreamOpen(height=FRAME, width=FRAME),
            )
        )
        it = call(gen())
        assert next(it).status == "OK"
        assert stream_manager.open_sessions() == before + 1
        q.put(None)  # end the RPC without a Close message
        with pytest.raises(StopIteration):
            next(it)
    finally:
        channel.close()
    deadline = threading.Event()
    for _ in range(50):
        if stream_manager.open_sessions() == before:
            break
        deadline.wait(0.05)
    assert stream_manager.open_sessions() == before
