"""Mesh data-plane tests on the virtual 8-device CPU mesh (conftest.py).

The load-bearing guarantee (SURVEY.md §4 "distributed-without-a-cluster"):
the single-program mesh round must produce the SAME global weights as the
host-loop path (per-client jitted train steps + host fedavg) — i.e.
mesh FedAvg == gRPC FedAvg == numpy mean.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.fed.algorithms import fedavg
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.parallel import (
    build_federated_round,
    make_mesh,
    mesh_fedavg,
    stack_client_data,
)
from fedcrack_tpu.train.local import create_train_state, train_step

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
STEPS, BATCH = 2, 4


def _client_data(n_clients, seed0=0):
    per_client = [
        synth_crack_batch(STEPS * BATCH, img_size=TINY.img_size, seed=seed0 + i)
        for i in range(n_clients)
    ]
    return stack_client_data(per_client, STEPS, BATCH)


def _assert_trees_match(got, want, atol=2e-5):
    """Tight comparison, except conv biases that feed straight into a
    BatchNorm: BN cancels an additive bias, so its true gradient is ~0 and
    Adam (scale-invariant) turns fp-reassociation noise between the two XLA
    programs into full lr-sized steps. Those leaves only get a loose bound
    (|update| <= ~lr * steps)."""
    gl = jax.tree_util.tree_leaves_with_path(got)
    wl = jax.tree_util.tree_leaves(want)
    assert len(gl) == len(wl)
    for (path, g), w in zip(gl, wl):
        key = jax.tree_util.keystr(path)
        bn_shadowed_bias = key.endswith("'bias']") and any(
            s in key for s in ("stem_conv", "_sep", "_convT")
        )
        np.testing.assert_allclose(
            np.asarray(g),
            np.asarray(w),
            atol=5e-3 if bn_shadowed_bias else atol,
            err_msg=key,
        )


def _host_round(
    variables, images, masks, active, n_samples, lr, epochs=1, pos_weight=1.0
):
    """Reference implementation: sequential jitted steps + host fedavg."""
    trained, weights = [], []
    for c in range(images.shape[0]):
        state = create_train_state(jax.random.key(0), TINY, lr)
        state = state.replace_variables(variables)
        for _ in range(epochs):
            for s in range(images.shape[1]):
                batch = (jnp.asarray(images[c, s]), jnp.asarray(masks[c, s]))
                state, _ = train_step(
                    state,
                    batch,
                    variables["params"],
                    jnp.float32(0.0),
                    jnp.float32(pos_weight),
                )
        if active[c]:
            trained.append(state.variables)
            weights.append(n_samples[c])
    return fedavg(trained, weights)


class TestMeshMatchesHost:
    def test_mesh_round_equals_host_round(self):
        mesh = make_mesh(8, 1)
        images, masks = _client_data(8)
        variables = create_train_state(jax.random.key(7), TINY).variables
        active = np.ones(8, np.float32)
        n_samples = np.array([8, 8, 8, 8, 16, 16, 8, 8], np.float32)

        round_fn = build_federated_round(mesh, TINY, learning_rate=1e-3)
        got, metrics = round_fn(variables, images, masks, active, n_samples)
        want = _host_round(variables, images, masks, active, n_samples, 1e-3)

        _assert_trees_match(got, want)
        assert metrics["loss"].shape == (8,)
        assert np.all(np.isfinite(np.asarray(metrics["loss"])))

    @pytest.mark.slow
    def test_pos_weight_round_equals_host_round(self):
        """Crack-pixel loss weighting must train identically on both planes
        (and actually change the trajectory vs plain BCE).

        Slow-marked (round-12 tier-1 budget re-balance, the r4/r9
        precedent): a second full mesh+host compile whose parity machinery
        is tier-1-pinned at pos_weight=1 by test_mesh_round_equals_host_round
        and whose pos_weight numerics are tier-1-pinned host-side by
        test_train/test_pallas_bce."""
        mesh = make_mesh(4, 1)
        images, masks = _client_data(4)
        variables = create_train_state(jax.random.key(11), TINY).variables
        active = np.ones(4, np.float32)
        n_samples = np.full(4, 8.0, np.float32)

        round_fn = build_federated_round(mesh, TINY, learning_rate=1e-3, pos_weight=5.0)
        got, _ = round_fn(variables, images, masks, active, n_samples)
        want = _host_round(variables, images, masks, active, n_samples, 1e-3, pos_weight=5.0)
        _assert_trees_match(got, want)
        plain = _host_round(variables, images, masks, active, n_samples, 1e-3)
        leaves_w = jax.tree_util.tree_leaves(want["params"])
        leaves_p = jax.tree_util.tree_leaves(plain["params"])
        assert any(not np.allclose(w, p) for w, p in zip(leaves_w, leaves_p))

    def test_masked_cohort_shrinks_divisor(self):
        """Dropped clients (active=0) must not pollute the average and the
        divisor must shrink — no recompilation (SURVEY.md §7)."""
        mesh = make_mesh(8, 1)
        images, masks = _client_data(8)
        variables = create_train_state(jax.random.key(3), TINY).variables
        active = np.array([1, 1, 1, 0, 0, 1, 1, 1], np.float32)
        n_samples = np.full(8, 8.0, np.float32)

        round_fn = build_federated_round(mesh, TINY, learning_rate=1e-3)
        got, _ = round_fn(variables, images, masks, active, n_samples)
        want = _host_round(variables, images, masks, active, n_samples, 1e-3)
        _assert_trees_match(got, want)

    def test_intra_client_batch_dp_matches_host(self):
        """4 clients x 2-way batch DP trains exactly like the single-device
        host path: BN is synced over the `batch` axis and gradients are
        mean (not sum) over the DP shards, so splitting a client's batch
        across chips must not change the result."""
        mesh = make_mesh(4, 2)
        images, masks = _client_data(4)
        variables = create_train_state(jax.random.key(1), TINY).variables
        active = np.ones(4, np.float32)
        n_samples = np.full(4, 8.0, np.float32)
        round_fn = build_federated_round(
            mesh, TINY, learning_rate=1e-3, local_epochs=2
        )
        got, metrics = round_fn(variables, images, masks, active, n_samples)
        want = _host_round(variables, images, masks, active, n_samples, 1e-3, epochs=2)
        # 2 epochs of cross-shard collectives accumulate a little more fp
        # reassociation noise than the batch=1 path.
        _assert_trees_match(got, want, atol=5e-5)
        assert metrics["loss"].shape == (4,)

    def test_dp_gradient_not_double_counted(self, monkeypatch):
        """Regression: `params` is batch-unvarying, so shard_map AD psums the
        grad cotangents over the `batch` axis; the step must divide by the
        shard count. With SGD(1.0) the applied update IS the gradient —
        duplicated batch halves make per-shard data identical, so the
        2-shard update must equal the 1-shard one (a double-count shows up
        as an exact 2x)."""
        import optax

        import fedcrack_tpu.parallel.fedavg_mesh as fm

        monkeypatch.setattr(fm, "make_optimizer", lambda lr: optax.sgd(1.0))
        imgs4, msks4 = synth_crack_batch(4, img_size=TINY.img_size, seed=0)
        images, masks = stack_client_data(
            [(np.concatenate([imgs4, imgs4]), np.concatenate([msks4, msks4]))],
            steps=1,
            batch_size=8,
        )
        variables = create_train_state(jax.random.key(0), TINY).variables
        active = np.ones(1, np.float32)
        n_samples = np.full(1, 8.0, np.float32)

        deltas = {}
        for nb in (1, 2):
            round_fn = fm.build_federated_round(
                make_mesh(1, nb), TINY, learning_rate=1.0, local_epochs=1
            )
            new_vars, _ = round_fn(variables, images, masks, active, n_samples)
            new_vars = jax.device_get(new_vars)
            deltas[nb] = jax.tree_util.tree_map(
                lambda old, new: np.asarray(old) - np.asarray(new),
                jax.device_get(variables)["params"],
                new_vars["params"],
            )
        g1 = jax.tree_util.tree_leaves(deltas[1])
        g2 = jax.tree_util.tree_leaves(deltas[2])
        ratio = sum(float(np.vdot(a, b)) for a, b in zip(g1, g2)) / sum(
            float(np.vdot(a, a)) for a in g1
        )
        assert 0.999 < ratio < 1.001, f"DP gradient scale off: ratio={ratio}"

    def test_all_dropped_cohort_raises(self):
        """active == 0 everywhere must raise, not silently zero the model
        (same contract as fed.algorithms.fedavg)."""
        mesh = make_mesh(8, 1)
        images, masks = _client_data(8)
        variables = create_train_state(jax.random.key(2), TINY).variables
        round_fn = build_federated_round(mesh, TINY)
        with pytest.raises(ValueError, match="non-positive"):
            round_fn(
                variables, images, masks,
                np.zeros(8, np.float32), np.full(8, 8.0, np.float32),
            )
        with pytest.raises(ValueError, match="non-positive"):
            mesh_fedavg({"k": np.ones((3, 2), np.float32)}, active=[0.0, 0.0, 0.0])

    def test_all_dropped_cohort_in_mesh_guard(self, monkeypatch):
        """In a multi-host job the cohort mask is a cross-process sharded
        array no single process can inspect, so the host-side ValueError
        can't fire; the IN-MESH guard must then return the incoming global
        model unchanged — never an all-zero psum average."""
        import fedcrack_tpu.parallel.fedavg_mesh as fm

        monkeypatch.setattr(fm, "_host_view", lambda x: None)
        mesh = make_mesh(8, 1)
        images, masks = _client_data(8)
        variables = create_train_state(jax.random.key(2), TINY).variables
        round_fn = build_federated_round(mesh, TINY)
        new_vars, metrics = round_fn(
            variables, images, masks,
            np.zeros(8, np.float32), np.full(8, 8.0, np.float32),
        )
        for got, want in zip(
            jax.tree_util.tree_leaves(new_vars), jax.tree_util.tree_leaves(variables)
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_fedprox_mu_changes_result(self):
        mesh = make_mesh(8, 1)
        images, masks = _client_data(8)
        variables = create_train_state(jax.random.key(5), TINY).variables
        ones, ns = np.ones(8, np.float32), np.full(8, 8.0, np.float32)
        plain = build_federated_round(mesh, TINY)(variables, images, masks, ones, ns)[0]
        prox = build_federated_round(mesh, TINY, fedprox_mu=10.0)(
            variables, images, masks, ones, ns
        )[0]
        diffs = [
            float(jnp.max(jnp.abs(a - b)))
            for a, b in zip(
                jax.tree_util.tree_leaves(plain["params"]),
                jax.tree_util.tree_leaves(prox["params"]),
            )
        ]
        assert max(diffs) > 1e-7


    def test_remat_round_matches_plain(self):
        """jax.checkpoint recomputes the forward during backward — the
        round's math is unchanged; only the activation-memory/FLOPs schedule
        moves. Guards the HBM lever for crops that don't otherwise fit."""
        mesh = make_mesh(4, 2)
        images, masks = _client_data(4)
        variables = create_train_state(jax.random.key(9), TINY).variables
        ones, ns = np.ones(4, np.float32), np.full(4, 8.0, np.float32)
        plain = build_federated_round(mesh, TINY)
        rematd = build_federated_round(mesh, TINY, remat=True)
        v_plain, m_plain = plain(variables, images, masks, ones, ns)
        v_remat, m_remat = rematd(variables, images, masks, ones, ns)
        np.testing.assert_allclose(
            np.asarray(m_plain["loss"]), np.asarray(m_remat["loss"]), rtol=1e-6
        )
        _assert_trees_match(v_remat["params"], v_plain["params"])

    def test_remat_spatial_round_matches_plain(self):
        """The riskier remat composition: checkpointing the halo-exchange
        spatial forward rematerializes ppermute + sync-BN collectives in
        the backward — this is the path remat exists for (crops too large
        per chip), so its parity is pinned separately."""
        from fedcrack_tpu.parallel import build_spatial_federated_round

        # Per-shard height must be a multiple of 16: 32px / 2 spatial shards.
        tiny32 = ModelConfig(
            img_size=32, stem_features=4, encoder_features=(8,),
            decoder_features=(8, 4),
        )
        per_client = [
            synth_crack_batch(STEPS * BATCH, img_size=32, seed=i) for i in range(4)
        ]
        images, masks = stack_client_data(per_client, STEPS, BATCH)
        mesh = make_mesh(4, 2, axis_names=("clients", "space"))
        variables = create_train_state(jax.random.key(9), tiny32).variables
        ones, ns = np.ones(4, np.float32), np.full(4, 8.0, np.float32)
        plain = build_spatial_federated_round(mesh, tiny32)
        rematd = build_spatial_federated_round(mesh, tiny32, remat=True)
        v_plain, m_plain = plain(variables, images, masks, ones, ns)
        v_remat, m_remat = rematd(variables, images, masks, ones, ns)
        np.testing.assert_allclose(
            np.asarray(m_plain["loss"]), np.asarray(m_remat["loss"]), rtol=1e-6
        )
        _assert_trees_match(v_remat["params"], v_plain["params"])

class TestLayoutTransformedRounds:
    """Round 6: the space-to-depth/channel-packed round programs are the
    SAME federation as the reference layout — not 'close', identical."""

    def test_s2d_round_weights_bit_identical_to_reference_round(self):
        """The exact transforms (stem 's2d' + residual 'packed') carry
        bit-exactness through a WHOLE mesh round — forward, backward, Adam,
        FedAvg — so the transformed round returns byte-identical global
        weights. (The forward is order-preserving-exact; on the CPU test
        backend the backward accumulates identically too, making this the
        strongest possible pin for the A/B's 'same math' claim.)"""
        mesh = make_mesh(4, 1)
        images, masks = _client_data(4)
        variables = create_train_state(jax.random.key(7), TINY).variables
        active = np.ones(4, np.float32)
        n_samples = np.full(4, 8.0, np.float32)

        import dataclasses as _dc

        ref_cfg = TINY
        s2d_cfg = _dc.replace(TINY, stem_layout="s2d", res_layout="packed")
        ref_fn = build_federated_round(mesh, ref_cfg, learning_rate=1e-3)
        s2d_fn = build_federated_round(mesh, s2d_cfg, learning_rate=1e-3)
        want, m_ref = ref_fn(variables, images, masks, active, n_samples)
        got, m_s2d = s2d_fn(variables, images, masks, active, n_samples)
        for (path, g), w in zip(
            jax.tree_util.tree_leaves_with_path(got), jax.tree_util.tree_leaves(want)
        ):
            assert np.array_equal(np.asarray(g), np.asarray(w)), (
                jax.tree_util.keystr(path)
            )
        np.testing.assert_array_equal(
            np.asarray(m_s2d["loss"]), np.asarray(m_ref["loss"])
        )

    def test_prepacked_staging_matches_unpacked(self):
        """Host-packed staging ([C,steps,B,H/2,W/2,4ch], the driver's
        transformed-layout staging shape) feeds the same round program
        family and produces the same weights as on-device packing."""
        from fedcrack_tpu.data.pipeline import space_to_depth_images

        mesh = make_mesh(4, 1)
        images, masks = _client_data(4)
        variables = create_train_state(jax.random.key(5), TINY).variables
        active = np.ones(4, np.float32)
        n_samples = np.full(4, 8.0, np.float32)
        import dataclasses as _dc

        s2d_cfg = _dc.replace(TINY, stem_layout="s2d")
        fn = build_federated_round(mesh, s2d_cfg, learning_rate=1e-3)
        got_unpacked, _ = fn(variables, images, masks, active, n_samples)
        got_packed, _ = fn(
            variables, space_to_depth_images(images), masks, active, n_samples
        )
        for g, w in zip(
            jax.tree_util.tree_leaves(got_packed),
            jax.tree_util.tree_leaves(got_unpacked),
        ):
            assert np.array_equal(np.asarray(g), np.asarray(w))

    def test_wrong_channel_staging_rejected(self):
        mesh = make_mesh(4, 1)
        images, masks = _client_data(4)
        variables = create_train_state(jax.random.key(5), TINY).variables
        fn = build_federated_round(mesh, TINY, learning_rate=1e-3)
        bad = np.concatenate([images, images], axis=-1)  # 6 channels
        with pytest.raises(ValueError, match="channels"):
            fn(variables, bad, masks, np.ones(4, np.float32), np.full(4, 8.0, np.float32))

    def test_spatial_round_rejects_transformed_layouts(self):
        import dataclasses as _dc

        from fedcrack_tpu.parallel import build_spatial_federated_round

        mesh = make_mesh(4, 2, axis_names=("clients", "space"))
        with pytest.raises(ValueError, match="reference layout"):
            build_spatial_federated_round(
                mesh, _dc.replace(TINY, stem_layout="s2d")
            )


class TestMeshFedavgGolden:
    def test_matches_numpy_mean(self):
        rng = np.random.default_rng(0)
        stacked = {
            "w": rng.normal(size=(4, 3, 3)).astype(np.float32),
            "b": rng.normal(size=(4, 5)).astype(np.float32),
        }
        got = mesh_fedavg(stacked)
        np.testing.assert_allclose(np.asarray(got["w"]), stacked["w"].mean(0), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(got["b"]), stacked["b"].mean(0), rtol=1e-6)

    def test_matches_host_fedavg_weighted(self):
        rng = np.random.default_rng(1)
        trees = [
            {"k": rng.normal(size=(2, 2)).astype(np.float32)} for _ in range(3)
        ]
        w = [1.0, 2.0, 5.0]
        stacked = {"k": np.stack([t["k"] for t in trees])}
        got = mesh_fedavg(stacked, weights=w)
        want = fedavg(trees, weights=w)
        np.testing.assert_allclose(np.asarray(got["k"]), np.asarray(want["k"]), rtol=1e-6)

    def test_active_mask(self):
        stacked = {"k": np.stack([np.full((2,), v, np.float32) for v in (1, 2, 9)])}
        got = mesh_fedavg(stacked, active=[1.0, 1.0, 0.0])
        np.testing.assert_allclose(np.asarray(got["k"]), np.full((2,), 1.5), rtol=1e-6)


class TestStackClientData:
    def test_shapes_and_cycling(self):
        imgs, msks = synth_crack_batch(5, img_size=16, seed=0)
        si, sm = stack_client_data([(imgs, msks)], steps=2, batch_size=4)
        assert si.shape == (1, 2, 4, 16, 16, 3)
        assert sm.shape == (1, 2, 4, 16, 16, 1)
        np.testing.assert_array_equal(si[0, 1, 1], imgs[0])  # sample 5 cycles to 0


class TestSpatialFederatedRound:
    def test_clients_by_space_matches_host(self):
        """4 clients x 2-way spatial sharding trains exactly like the
        single-device host path: halo-exchange conv + sync-BN over the
        space axis, mean gradients, FedAvg over clients."""
        from fedcrack_tpu.parallel import build_spatial_federated_round
        from fedcrack_tpu.parallel.mesh import make_mesh as mm

        # Per-shard height must be a multiple of 16: 32px / 2 spatial shards.
        tiny32 = ModelConfig(
            img_size=32, stem_features=4, encoder_features=(8,),
            decoder_features=(8, 4),
        )
        per_client = [
            synth_crack_batch(STEPS * BATCH, img_size=32, seed=i) for i in range(4)
        ]
        images, masks = stack_client_data(per_client, STEPS, BATCH)
        variables = create_train_state(jax.random.key(2), tiny32).variables
        active = np.ones(4, np.float32)
        n_samples = np.full(4, 8.0, np.float32)

        mesh = mm(4, 2, axis_names=("clients", "space"))
        round_fn = build_spatial_federated_round(
            mesh, tiny32, learning_rate=1e-3, local_epochs=2
        )
        got, metrics = round_fn(variables, images, masks, active, n_samples)

        # Host reference on the same 32px config.
        trained, weights = [], []
        for c in range(4):
            state = create_train_state(jax.random.key(0), tiny32, 1e-3)
            state = state.replace_variables(variables)
            for _ in range(2):
                for s in range(STEPS):
                    state, _ = train_step(
                        state,
                        (jnp.asarray(images[c, s]), jnp.asarray(masks[c, s])),
                        variables["params"],
                        jnp.float32(0.0),
                    )
            trained.append(state.variables)
            weights.append(n_samples[c])
        want = fedavg(trained, weights)

        # 1e-4: the host path takes the scatter-free pool backward
        # (ops/pooling.py) while the spatial path pools through its halo
        # reduce_window with XLA's default gradient — same routing, different
        # summation order, so the per-step ulp noise compounds slightly more
        # than the pre-custom-pool 5e-5 calibration allowed.
        _assert_trees_match(got, want, atol=1e-4)
        assert np.all(np.isfinite(np.asarray(metrics["loss"])))

    def test_rejects_misaligned_height(self):
        from fedcrack_tpu.parallel import build_spatial_federated_round
        from fedcrack_tpu.parallel.mesh import make_mesh as mm

        mesh = mm(2, 4, axis_names=("clients", "space"))  # needs H % 64 == 0
        round_fn = build_spatial_federated_round(mesh, TINY)
        images, masks = _client_data(2)  # H = 32
        with pytest.raises(ValueError, match="multiple of 16"):
            round_fn(
                create_train_state(jax.random.key(0), TINY).variables,
                images,
                masks,
                np.ones(2, np.float32),
                np.full(2, 8.0, np.float32),
            )
