"""gRPC control plane: codec mapping and an in-process federated session.

The integration test is the SURVEY.md §4 "in-process server + K fake clients
over localhost gRPC" check: round count, version monotonicity, and broadcast
weights == average of uploads (regression tests for the reference bugs
§2.2(1,2))."""

import dataclasses
import threading

import grpc
import numpy as np
import pytest

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.transport import FedClient, FedServer
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.codec import (
    decode_scalar_map,
    encode_scalar_map,
    event_from_message,
    message_from_reply,
)
from fedcrack_tpu.transport.service import ServerThread


# ---------- codec ----------

def test_scalar_map_roundtrip():
    msg = pb.ServerMessage()
    values = {"i": 3, "f": 0.5, "s": "SW", "b": True, "by": b"\x00\x01"}
    encode_scalar_map(msg.config, values)
    assert decode_scalar_map(msg.config) == values


def test_event_mapping_all_kinds():
    m = pb.ClientMessage(cname="c")
    m.ready.SetInParent()
    assert isinstance(event_from_message(m, 1.0), R.Ready)
    m = pb.ClientMessage(cname="c")
    m.pull.SetInParent()
    assert isinstance(event_from_message(m, 1.0), R.PullWeights)
    m = pb.ClientMessage(cname="c")
    m.training.round = 2
    assert isinstance(event_from_message(m, 1.0), R.TrainingNotice)
    m = pb.ClientMessage(cname="c")
    m.log.title = "t"
    m.log.data = b"d"
    ev = event_from_message(m, 1.0)
    assert isinstance(ev, R.LogChunk) and ev.data == b"d"
    m = pb.ClientMessage(cname="c")
    m.done.round = 1
    m.done.weights = b"w"
    m.done.sample_count = 9
    ev = event_from_message(m, 1.0)
    assert isinstance(ev, R.TrainDone) and ev.num_samples == 9
    m = pb.ClientMessage(cname="c")
    m.poll.model_version = 1
    m.poll.round = 2
    ev = event_from_message(m, 1.0)
    assert isinstance(ev, R.VersionPoll) and ev.model_version == 1
    with pytest.raises(ValueError):
        event_from_message(pb.ClientMessage(cname="c"), 1.0)


def test_reply_mapping():
    out = message_from_reply(
        R.Reply(status="RESP_ARY", config={"current_round": 2}, blob=b"W", title="p")
    )
    assert out.status == "RESP_ARY"
    assert out.weights == b"W" and out.title == "p"
    assert decode_scalar_map(out.config)["current_round"] == 2


# ---------- integration: K fake clients over localhost ----------

def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _fake_train(increment: float, samples: int):
    """A 'trainer' that adds a constant — makes the expected average exact."""

    def train_fn(blob: bytes, rnd: int):
        tree = tree_from_bytes(blob)
        tree["params"]["w"] = tree["params"]["w"] + increment
        return tree_to_bytes(tree), samples, {"loss": float(rnd)}

    return train_fn


@pytest.fixture
def session_cfg():
    return FedConfig(
        max_rounds=3,
        cohort_size=2,
        registration_window_s=5.0,
        poll_period_s=0.05,
        host="127.0.0.1",
        port=0,  # ephemeral
    )


def test_two_clients_full_session(session_cfg):
    server = FedServer(session_cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        clients = [
            FedClient(session_cfg, _fake_train(1.0, 10), cname="a", port=st.port),
            FedClient(session_cfg, _fake_train(3.0, 30), cname="b", port=st.port),
        ]
        results = [None, None]
        threads = [
            threading.Thread(target=lambda i=i, c=c: results.__setitem__(i, c.run_session()))
            for i, c in enumerate(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        state = st.state

    assert all(r is not None and r.enrolled for r in results)
    assert all(r.rounds_completed == 3 for r in results)
    assert state.phase == R.PHASE_FINISHED
    assert state.current_round == 4 and state.model_version == 3
    assert len(state.history) == 3
    # weighted average: (10*(w+1) + 30*(w+3)) / 40 = w + 2.5 each round
    final = tree_from_bytes(state.global_blob)
    assert np.allclose(final["params"]["w"], 0.0 + 2.5 * 3, atol=1e-5)
    # both clients ended with the same (broadcast) weights == server average
    for r in results:
        got = tree_from_bytes(r.final_weights)
        assert np.allclose(got["params"]["w"], final["params"]["w"], atol=1e-5)


def test_late_client_turned_away(session_cfg):
    server = FedServer(session_cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        a = FedClient(session_cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        b = FedClient(session_cfg, _fake_train(1.0, 10), cname="b", port=st.port)
        ra = [None]
        rb = [None]
        ta = threading.Thread(target=lambda: ra.__setitem__(0, a.run_session()))
        tb = threading.Thread(target=lambda: rb.__setitem__(0, b.run_session()))
        ta.start()
        tb.start()
        ta.join(60)
        tb.join(60)
        # cohort full (2) -> enrollment closed -> latecomer gets CTW
        late = FedClient(session_cfg, _fake_train(1.0, 10), cname="late", port=st.port)
        rl = late.run_session()
    assert ra[0].enrolled and rb[0].enrolled
    assert not rl.enrolled and rl.rounds_completed == 0


def test_dead_client_mid_round_cohort_shrinks(session_cfg):
    """Fault injection (SURVEY.md §5.3): one client dies after round 1; the
    deadline shrinks the cohort and the survivor finishes alone.

    The deadline is only here to drop the DEAD client — but it also races
    the live one: a scheduler stall past it before the survivor's upload
    lands either shrinks the cohort around the survivor (round 1) or fires
    the zero-reports reopen (rounds 2-3), and the survivor's upload draws
    'not in cohort'. 0.5 s flaked ~1/6 on this host's ~0.5-1 s ambient
    stalls (pre-existing, seed-reproducible); 2.5 s clears them while
    costing only the two post-death round waits."""
    cfg = dataclasses.replace(session_cfg, round_deadline_s=2.5)
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)

    class DiesAfterRound1(Exception):
        pass

    def dying_train(blob, rnd):
        if rnd >= 2:
            raise DiesAfterRound1()
        return _fake_train(1.0, 10)(blob, rnd)

    with ServerThread(server) as st:
        a = FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        b = FedClient(cfg, dying_train, cname="b", port=st.port)
        res = {}

        def run(c, key):
            try:
                res[key] = c.run_session()
            except Exception as e:
                res[key] = e

        ta = threading.Thread(target=run, args=(a, "a"))
        tb = threading.Thread(target=run, args=(b, "b"))
        ta.start()
        tb.start()
        ta.join(60)
        tb.join(60)
        state = st.state

    assert isinstance(res["b"], DiesAfterRound1)
    assert not isinstance(res["a"], Exception)
    assert res["a"].rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    assert state.cohort == frozenset({"a"})


def test_crashed_client_restart_rejoins_and_completes(session_cfg):
    """Crash-restart-rejoin e2e: a cohort member that dies mid-round restarts
    under the same cname, re-enrolls mid-run (SW, not CTW), and the
    federation completes with the full cohort — no deadline shrink needed."""
    server = FedServer(session_cfg, _vars(0.0), tick_period_s=0.05)

    class Crash(Exception):
        pass

    calls = {"n": 0}

    def crashy_train(blob, rnd):
        calls["n"] += 1
        if calls["n"] == 2:  # dies during its second local fit (round 2)
            raise Crash()
        return _fake_train(1.0, 10)(blob, rnd)

    with ServerThread(server) as st:
        a = FedClient(session_cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        b1 = FedClient(session_cfg, crashy_train, cname="b", port=st.port)
        res = {}

        def run(c, key):
            try:
                res[key] = c.run_session()
            except Exception as e:
                res[key] = e

        ta = threading.Thread(target=run, args=(a, "a"))
        tb = threading.Thread(target=run, args=(b1, "b1"))
        ta.start()
        tb.start()
        tb.join(60)
        assert isinstance(res["b1"], Crash)
        # restart under the same cname: must re-enroll and finish the run
        b2 = FedClient(session_cfg, _fake_train(1.0, 10), cname="b", port=st.port)
        run(b2, "b2")
        ta.join(60)
        state = st.state

    assert not isinstance(res["a"], Exception)
    assert res["b2"].enrolled, "restarted cohort member was locked out"
    assert res["a"].rounds_completed == 3
    assert res["b2"].rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    assert state.cohort == frozenset({"a", "b"})
    assert len(state.history) == 3


def test_auth_token_gates_every_message(session_cfg):
    """Control-plane authentication: the right token completes a session;
    a wrong (or missing) token is REJECTED at enrollment and an
    already-authenticated flow's uploads are still checked per-message.
    The token is deliberately non-ASCII: the comparison must be over
    utf-8 bytes (str-domain compare_digest raises on non-ASCII)."""
    cfg = dataclasses.replace(
        session_cfg,
        cohort_size=1,
        auth_token="s3crét-käy",
        allow_insecure_token=True,  # loopback test: plaintext token opt-in
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        ok = FedClient(cfg, _fake_train(1.0, 10), cname="good", port=st.port)
        r_ok = ok.run_session()
    assert r_ok.enrolled and r_ok.rounds_completed == 3

    server2 = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server2) as st:
        bad_cfg = dataclasses.replace(cfg, auth_token="wrong")
        bad = FedClient(bad_cfg, _fake_train(1.0, 10), cname="evil", port=st.port)
        r_bad = bad.run_session()
        noauth_cfg = dataclasses.replace(cfg, auth_token="")
        noauth = FedClient(
            noauth_cfg, _fake_train(1.0, 10), cname="anon", port=st.port
        )
        r_noauth = noauth.run_session()
        state = st.state
    assert not r_bad.enrolled and not r_noauth.enrolled
    assert state.cohort == frozenset()  # nothing reached the state machine


def test_auth_token_over_plaintext_refused_without_optin(session_cfg):
    """A shared token over a plaintext channel ships the secret in cleartext
    on every message; the config refuses the combination unless opted into
    by name (round-3 advisor + VERDICT weak #4)."""
    with pytest.raises(ValueError, match="plaintext"):
        dataclasses.replace(session_cfg, auth_token="s3cret")
    # explicit opt-in or any TLS half resolves it
    dataclasses.replace(session_cfg, auth_token="s3cret", allow_insecure_token=True)
    dataclasses.replace(session_cfg, auth_token="s3cret", tls_ca="/some/ca.pem")
    # Role-aware: a CLIENT holding a server-shaped config (tls_cert/tls_key
    # but no tls_ca) passes config validation — it is a valid SERVER config —
    # but only tls_ca encrypts the client channel, so dialing must refuse.
    srv_shaped = dataclasses.replace(
        session_cfg, auth_token="s3cret", tls_cert="/c.pem", tls_key="/k.pem"
    )
    client = FedClient(srv_shaped, _fake_train(1.0, 10), cname="x", port=1)
    with pytest.raises(ValueError, match="plaintext client channel"):
        client._connect()


def test_unauthenticated_stream_terminates_after_rejection(session_cfg):
    """After the first failed token check the server ends the stream: a peer
    without the token must not keep one RPC open feeding arbitrarily many
    (up to max_message_mb) messages through receive+parse (round-3 advisor).
    A well-behaved client is unaffected — it sends one message per call."""
    import grpc

    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import METHOD, SERVICE_NAME

    cfg = dataclasses.replace(
        session_cfg, auth_token="s3cret", allow_insecure_token=True
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        channel = grpc.insecure_channel(f"127.0.0.1:{st.port}")
        method = channel.stream_stream(
            f"/{SERVICE_NAME}/{METHOD}",
            request_serializer=pb.ClientMessage.SerializeToString,
            response_deserializer=pb.ServerMessage.FromString,
        )
        bad = pb.ClientMessage(cname="evil", token="wrong")
        bad.ready.SetInParent()
        # Two unauthenticated messages on ONE stream: the first is answered
        # REJECTED, then the stream closes — exactly one reply comes back.
        replies = list(method(iter([bad, bad]), timeout=10, wait_for_ready=True))
        assert [r.status for r in replies] == [R.REJECTED]
        channel.close()
        state = st.state
    assert state.cohort == frozenset()


def test_partial_tls_config_fails_fast():
    """Half a TLS identity must not silently serve plaintext."""
    with pytest.raises(ValueError, match="tls_cert and tls_key"):
        FedConfig(tls_cert="/some/cert.pem")
    with pytest.raises(ValueError, match="tls_cert and tls_key"):
        FedConfig(tls_key="/some/key.pem")


def test_server_with_ca_only_refuses_to_bind_plaintext():
    """tls_ca alone is a client config; a SERVER launched with it must not
    silently bind a plaintext port while the operator believes mTLS is on."""
    cfg = FedConfig(port=0, tls_ca="/some/ca.pem")
    server = FedServer(cfg, _vars(0.0))
    with pytest.raises(ValueError, match="mTLS"):
        server._build()


def _self_signed_cert(tmp_path):
    """A throwaway self-signed cert for 127.0.0.1 (valid as its own CA)."""
    import datetime
    import ipaddress

    pytest.importorskip("cryptography")  # not a package dependency: skip, not error
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / "cert.pem"
    key_path = tmp_path / "key.pem"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


def test_tls_session_and_plaintext_refused(session_cfg, tmp_path):
    """TLS server credentials as a config option: a TLS+token client
    completes; a plaintext client cannot even open the stream."""
    cert, key = _self_signed_cert(tmp_path)
    server_cfg = dataclasses.replace(
        session_cfg,
        cohort_size=1,
        max_rounds=1,
        auth_token="s3cret",
        tls_cert=cert,
        tls_key=key,
    )
    server = FedServer(server_cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        # client verifies the self-signed server cert as its root
        client_cfg = dataclasses.replace(server_cfg, tls_cert="", tls_key="", tls_ca=cert)
        ok = FedClient(client_cfg, _fake_train(1.0, 10), cname="tls", port=st.port)
        r_ok = ok.run_session()
        assert r_ok.enrolled and r_ok.rounds_completed == 1

    server2 = FedServer(server_cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server2) as st:
        plain_cfg = dataclasses.replace(
            server_cfg, tls_cert="", tls_key="", allow_insecure_token=True
        )
        plain = FedClient(
            plain_cfg, _fake_train(1.0, 10), cname="plain", port=st.port,
            max_retries=2, call_timeout_s=5.0,
        )
        with pytest.raises(grpc.RpcError):
            plain.run_session()
        assert st.state.cohort == frozenset()


def test_safe_component_injective():
    """Distinct untrusted wire names must never map to the same file — e.g.
    titles 'a/b' and 'a_b' previously both became 'a_b', letting one client
    upload silently overwrite another's log."""
    from fedcrack_tpu.transport.service import _safe_component

    names = ["a/b", "a_b", "a\\b", "..", "_", " a_b ", "a..b", "a_b.12ab34cd", ".."]
    mapped = [_safe_component(n) for n in names]
    # injective over distinct inputs
    assert len(set(mapped)) == len(set(names))
    # still never a traversal component
    for comp in mapped:
        assert "/" not in comp and "\\" not in comp and ".." not in comp
        assert not comp.startswith(".")
    # forging another client's sanitized-form name (the digest is computable
    # by anyone) must not land on that client's file either
    assert _safe_component(_safe_component("a/b")) != _safe_component("a/b")
    # already-safe names pass through unchanged (stable on-disk layout)
    assert _safe_component("client-metrics.jsonl") == "client-metrics.jsonl"


def test_chunked_log_upload_roundtrip(session_cfg, tmp_path):
    """C2.1/C1.5: the client streams a file in chunks; the server accumulates
    and flushes it to logs_dir on the last chunk, with untrusted names
    sanitized (the reference's path came from title[11:] string surgery,
    fl_server.py:84-89)."""
    cfg = dataclasses.replace(session_cfg, cohort_size=1, logs_dir=str(tmp_path / "sink"))
    payload = bytes(range(256)) * 1024  # 256 KiB, multiple chunks at 64 KiB
    src = tmp_path / "client-metrics.jsonl"
    src.write_bytes(payload)

    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        client = FedClient(
            cfg, _fake_train(1.0, 10), cname="a", port=st.port,
            upload_paths=(str(src),),
        )
        result = client.run_session()  # session-end upload of upload_paths
        # upload_file standalone (the sink only accepts cohort members, so
        # this runs post-enrollment); small chunks to force several messages
        client.upload_file(str(src), title="../evil/../../escape", chunk_bytes=64 * 1024)
        state = st.state

    assert result.rounds_completed == cfg.max_rounds
    # flushed buffers are dropped from memory (unbounded-growth guard)
    assert state.logs == {}
    # disk flush: sanitized path inside the sink, exact bytes. A rewritten
    # name carries a hash suffix of the original bytes (injectivity — two
    # distinct wire names can never collapse onto one file); an already-safe
    # name like the metrics filename passes through untouched.
    import hashlib

    evil = "__evil_____escape." + hashlib.sha256(b"../evil/../../escape").hexdigest()[:16]
    sink = tmp_path / "sink"
    flushed = sorted(p for p in sink.rglob("*") if p.is_file())
    assert [p.name for p in flushed] == sorted(
        [evil, "client-metrics.jsonl"]
    ), flushed
    for p in flushed:
        assert p.read_bytes() == payload
        assert p.parent == sink / "a"
        assert sink in p.parents  # no traversal out of the sink


def test_corrupt_log_chunk_rejected(session_cfg, tmp_path):
    """Integrity framing: a chunk whose declared CRC32C does not match its
    bytes must be REJECTED (and never flushed), and the uploader must fail
    loudly on the rejection. The reference shipped 100 MB chunks with no
    checksums at all (fl_client.py:35-50)."""
    import grpc

    from fedcrack_tpu.native import crc32c
    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import METHOD, SERVICE_NAME

    cfg = dataclasses.replace(
        session_cfg, cohort_size=1, logs_dir=str(tmp_path / "sink")
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        channel = grpc.insecure_channel(f"127.0.0.1:{st.port}")
        method = channel.stream_stream(
            f"/{SERVICE_NAME}/{METHOD}",
            request_serializer=pb.ClientMessage.SerializeToString,
            response_deserializer=pb.ServerMessage.FromString,
        )

        def call(msg):
            return next(iter(method(iter([msg]), timeout=10, wait_for_ready=True)))

        # enroll first: the sink only accepts cohort members
        ready = pb.ClientMessage(cname="a")
        ready.ready.SetInParent()
        assert call(ready).status == R.SW

        good = pb.ClientMessage(cname="a")
        good.log.title = "m"
        good.log.data = b"intact bytes"
        good.log.offset = 0
        good.log.crc32c = crc32c(b"intact bytes")
        assert call(good).status == "OK"

        bad = pb.ClientMessage(cname="a")
        bad.log.title = "m"
        bad.log.data = b"corrupted!!"
        bad.log.offset = len(good.log.data)
        bad.log.last = True
        bad.log.crc32c = crc32c(b"what was sent")
        rep = call(bad)
        assert rep.status == R.REJECTED
        assert "checksum mismatch" in rep.title
        channel.close()
        state = st.state

    # nothing flushed (the rejected chunk was the flush trigger) and the
    # buffer still holds only the verified bytes
    assert not (tmp_path / "sink").exists() or not any((tmp_path / "sink").rglob("*"))
    assert state.logs.get("a/m") == b"intact bytes"


def test_server_side_eval_runs_per_round(session_cfg, tmp_path):
    """The reference designed per-round eval of the fresh global model but
    never enabled it (trainNextRound, fl_server.py:27-37); here it runs
    after every aggregation, off the serving path."""
    calls = []

    def eval_fn(blob):
        tree = tree_from_bytes(blob)
        calls.append(float(tree["params"]["w"].mean()))
        return {"loss": 0.5, "iou": 0.25}

    cfg = dataclasses.replace(session_cfg, cohort_size=1, max_rounds=2)
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05, eval_fn=eval_fn)
    with ServerThread(server) as st:
        result = FedClient(
            cfg, _fake_train(1.0, 10), cname="a", port=st.port
        ).run_session()

    assert result.rounds_completed == 2
    assert len(server.eval_history) == 2
    # evaluated the AGGREGATED weights of each round (w + 1, then w + 2)
    assert calls == [pytest.approx(1.0), pytest.approx(2.0)]
    assert server.eval_history[0]["round"] == 1
    assert server.eval_history[1]["model_version"] == 2
    assert all(e["loss"] == 0.5 for e in server.eval_history)


def test_best_global_model_retained_by_eval_loss(session_cfg, tmp_path):
    """config.best_path keeps the best-by-eval-loss aggregated model — the
    federated analog of the reference's best-val ModelCheckpoint
    (test/Segmentation.py:177-179). Later worse rounds must NOT overwrite
    it; the sidecar records which round earned the file."""
    import json

    losses = iter([0.9, 0.2, 0.7])  # best is round 2

    def eval_fn(blob):
        return {"loss": next(losses)}

    best = tmp_path / "best" / "global.msgpack"
    cfg = dataclasses.replace(
        session_cfg, cohort_size=1, max_rounds=3, best_path=str(best)
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05, eval_fn=eval_fn)
    with ServerThread(server) as st:
        result = FedClient(
            cfg, _fake_train(1.0, 10), cname="a", port=st.port
        ).run_session()

    assert result.rounds_completed == 3
    assert server.best_eval is not None and server.best_eval["loss"] == 0.2
    # The file holds round 2's aggregated weights (w=0 + 1 + 1), not round 3's.
    tree = tree_from_bytes(best.read_bytes())
    np.testing.assert_allclose(tree["params"]["w"], 2.0)
    side = json.loads((tmp_path / "best" / "global.msgpack.json").read_text())
    assert side["round"] == 2 and side["loss"] == 0.2
    # The sidecar's content hash binds it to the model file (detects a crash
    # between the two renames).
    import hashlib

    assert side["sha256"] == hashlib.sha256(best.read_bytes()).hexdigest()

    # Restart semantics: a new server seeded from the same best_path must
    # NOT let a worse first eval overwrite the on-disk best...
    server2 = FedServer(
        cfg, _vars(0.0), tick_period_s=0.05, eval_fn=lambda blob: {"loss": 0.8}
    )
    assert server2.best_eval is not None and server2.best_eval["loss"] == 0.2
    with ServerThread(server2) as st:
        FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port).run_session()
    side2 = json.loads((tmp_path / "best" / "global.msgpack.json").read_text())
    assert side2["loss"] == 0.2  # the 0.8 post-restart evals never overwrote it


def test_best_model_rejects_non_finite_loss(session_cfg, tmp_path):
    """A NaN first eval must never be admitted as 'best' — NaN compares
    False against everything, which would pin a diverged model forever."""
    import json
    import math

    losses = iter([float("nan"), 0.4])

    def eval_fn(blob):
        return {"loss": next(losses)}

    best = tmp_path / "global.msgpack"
    cfg = dataclasses.replace(
        session_cfg, cohort_size=1, max_rounds=2, best_path=str(best)
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05, eval_fn=eval_fn)
    with ServerThread(server) as st:
        FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port).run_session()
    assert server.best_eval is not None and server.best_eval["loss"] == 0.4
    side = json.loads((tmp_path / "global.msgpack.json").read_text())
    assert math.isfinite(side["loss"]) and side["round"] == 2


def test_handshake_hyperparameters_reach_trainer(session_cfg):
    """The server's local_epochs / learning_rate / fedprox_mu ride the
    enroll handshake config map and are handed to the client's train_fn —
    one coordinator configures the cohort (the reference hardcoded these
    client-side, SURVEY.md §2.2(4))."""
    cfg = dataclasses.replace(
        session_cfg,
        cohort_size=1,
        max_rounds=1,
        local_epochs=7,
        learning_rate=0.005,
        fedprox_mu=0.125,
    )
    seen = []

    def train_fn(blob, rnd, hparams):
        seen.append(dict(hparams))
        return _fake_train(1.0, 10)(blob, rnd)

    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        # The CLIENT-side config deliberately disagrees with the server's.
        client_cfg = dataclasses.replace(cfg, local_epochs=1, fedprox_mu=0.0)
        result = FedClient(client_cfg, train_fn, cname="a", port=st.port).run_session()

    assert result.rounds_completed == 1
    assert seen == [
        {
            "local_epochs": 7,
            "learning_rate": 0.005,
            "fedprox_mu": 0.125,
            "wire_dtype": "float32",
            "update_codec": "null",
            "topk_fraction": 0.01,
        }
    ]
