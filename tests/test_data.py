"""Data pipeline: tensor contract, split determinism, pairing, sharding."""

import numpy as np
import pytest

from fedcrack_tpu.data import (
    CrackDataset,
    list_pairs,
    load_example,
    partition_iid,
    partition_skew,
    reference_split,
    synth_crack_batch,
    write_synthetic_dataset,
)
from fedcrack_tpu.data.sharding import crack_density


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    root = tmp_path_factory.mktemp("crackds")
    return write_synthetic_dataset(str(root), n=24, img_size=64, seed=7)


def test_synth_contract():
    images, masks = synth_crack_batch(4, img_size=64, seed=0)
    assert images.shape == (4, 64, 64, 3) and images.dtype == np.float32
    assert masks.shape == (4, 64, 64, 1) and masks.dtype == np.float32
    assert images.min() >= 0.0 and images.max() <= 1.0
    assert set(np.unique(masks)) <= {0.0, 1.0}


def test_synth_deterministic():
    a = synth_crack_batch(2, 32, seed=3)
    b = synth_crack_batch(2, 32, seed=3)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])


def test_list_pairs_matches_by_stem(fixture_dirs):
    image_dir, mask_dir = fixture_dirs
    pairs = list_pairs(image_dir, mask_dir)
    assert len(pairs) == 24
    for img_path, mask_path in pairs:
        import os

        assert os.path.splitext(os.path.basename(img_path))[0] == os.path.splitext(
            os.path.basename(mask_path)
        )[0]


def test_disk_masks_lossless_roundtrip(fixture_dirs):
    """On-disk fixture masks must binarize back to the generated masks exactly
    (JPEG artifacts would leak spurious crack pixels through '>0')."""
    image_dir, mask_dir = fixture_dirs
    _, masks = synth_crack_batch(24, img_size=64, seed=7)
    pairs = list_pairs(image_dir, mask_dir)
    for i, (_, mask_path) in enumerate(pairs):
        _, loaded = load_example(pairs[i][0], mask_path, img_size=64)
        assert np.array_equal(loaded[:, :, 0], masks[i, :, :, 0]), f"mask {i} corrupted"


def test_early_consumer_exit_does_not_strand_producer(fixture_dirs):
    import threading

    pairs = list_pairs(*fixture_dirs)
    before = threading.active_count()
    for _ in range(3):
        ds = CrackDataset(pairs, img_size=64, batch_size=2, prefetch=1, num_workers=2)
        it = iter(ds)
        next(it)
        it.close()  # early exit mid-epoch
    assert threading.active_count() <= before + 1, "producer threads leaked"


def test_load_example_binarizes_and_scales(fixture_dirs):
    image_dir, mask_dir = fixture_dirs
    pairs = list_pairs(image_dir, mask_dir)
    image, mask = load_example(*pairs[0], img_size=64)
    assert image.shape == (64, 64, 3) and 0.0 <= image.min() and image.max() <= 1.0
    assert mask.shape == (64, 64, 1)
    assert set(np.unique(mask)) <= {0.0, 1.0}


def test_reference_split_deterministic_and_disjoint(fixture_dirs):
    pairs = list_pairs(*fixture_dirs)
    tr1, va1 = reference_split(pairs, train_samples=16, seed=1337)
    tr2, va2 = reference_split(pairs, train_samples=16, seed=1337)
    assert tr1 == tr2 and va1 == va2
    assert len(tr1) == 16 and len(va1) == 8
    assert not (set(tr1) & set(va1))


def test_dataset_static_batches_and_prefetch(fixture_dirs):
    pairs = list_pairs(*fixture_dirs)
    ds = CrackDataset(pairs, img_size=64, batch_size=5, seed=0, num_workers=2)
    batches = list(ds)
    assert len(batches) == 4  # 24 // 5, last partial dropped (static shapes)
    for images, masks in batches:
        assert images.shape == (5, 64, 64, 3)
        assert masks.shape == (5, 64, 64, 1)


def test_dataset_reshuffles_between_epochs(fixture_dirs):
    pairs = list_pairs(*fixture_dirs)
    ds = CrackDataset(pairs, img_size=64, batch_size=24, seed=0, num_workers=0)
    (e1, _), (e2, _) = next(iter(ds)), next(iter(ds))
    assert not np.array_equal(e1, e2)


def test_partition_iid_disjoint_cover():
    shards = partition_iid(103, 8, seed=1)
    all_idx = np.concatenate(shards)
    assert len(all_idx) == 103
    assert len(np.unique(all_idx)) == 103
    sizes = [len(s) for s in shards]
    assert max(sizes) - min(sizes) <= 1


def test_partition_skew_disjoint_cover_and_skewed():
    rng = np.random.default_rng(0)
    scores = rng.uniform(size=200)
    shards = partition_skew(scores, 4, alpha=0.05, seed=0)
    all_idx = np.concatenate(shards)
    assert len(all_idx) == 200 and len(np.unique(all_idx)) == 200
    # with tiny alpha each client's mean score should be well separated
    means = sorted(float(np.mean(scores[s])) for s in shards)
    assert means[-1] - means[0] > 0.3


def test_crack_density():
    _, masks = synth_crack_batch(6, 32, seed=0, crack_prob=1.0)
    d = crack_density(masks)
    assert d.shape == (6,)
    assert (d > 0).all()


def test_dataset_from_source_synthetic_clamps_batch():
    from fedcrack_tpu.data import dataset_from_source

    ds = dataset_from_source(
        4, None, None, img_size=32, batch_size=16, drop_last=False
    )
    batches = list(ds)
    assert sum(b[0].shape[0] for b in batches) == 4  # every sample seen


def test_dataset_from_source_dirs_and_filter(tmp_path):
    from fedcrack_tpu.data import dataset_from_source, write_synthetic_dataset

    write_synthetic_dataset(str(tmp_path), 6, img_size=32)
    ds = dataset_from_source(
        0,
        str(tmp_path / "images"),
        str(tmp_path / "masks"),
        img_size=32,
        batch_size=4,
        pair_filter=lambda pairs: pairs[:3],
    )
    assert len(ds.pairs) == 3 and ds.batch_size == 3  # clamped

    with pytest.raises(ValueError, match="no image/mask pairs"):
        dataset_from_source(
            0,
            str(tmp_path / "images"),
            str(tmp_path / "masks"),
            img_size=32,
            batch_size=4,
            pair_filter=lambda pairs: [],
        )

    with pytest.raises(ValueError, match="image-dir"):
        dataset_from_source(0, None, None, img_size=32, batch_size=4)


def test_shard_pairs_disjoint_cover_iid_and_skew(tmp_path):
    from fedcrack_tpu.data import list_pairs, write_synthetic_dataset
    from fedcrack_tpu.data.sharding import shard_pairs

    write_synthetic_dataset(str(tmp_path), 12, img_size=32)
    pairs = list_pairs(str(tmp_path / "images"), str(tmp_path / "masks"))

    for kind in ("iid", "skew"):
        shards = [shard_pairs(pairs, 3, i, partition=kind, seed=7) for i in range(3)]
        flat = [p for s in shards for p in s]
        assert sorted(flat) == sorted(pairs), kind  # disjoint + cover
        # deterministic: every process computes the same assignment
        again = shard_pairs(pairs, 3, 1, partition=kind, seed=7)
        assert again == shards[1], kind

    assert shard_pairs(pairs, 1, 0) == list(pairs)
    with pytest.raises(ValueError, match="out of range"):
        shard_pairs(pairs, 3, 3)
    with pytest.raises(ValueError, match="unknown partition"):
        shard_pairs(pairs, 3, 0, partition="sorted")


def test_partition_skew_no_empty_shards():
    from fedcrack_tpu.data.sharding import partition_skew

    # Small dataset vs many clients: Dirichlet draws can zero out a client's
    # floor counts — the rebalance must leave every shard non-empty.
    for seed in range(6):
        shards = partition_skew(np.linspace(0, 1, 24), 8, alpha=0.1, seed=seed)
        assert all(len(s) > 0 for s in shards), seed
        flat = np.concatenate(shards)
        assert sorted(flat.tolist()) == list(range(24)), seed


def test_uint8_transport_bit_identical(fixture_dirs):
    """uint8 staging must be EXACTLY the float32 pipeline: the decode path
    resizes in uint8 before normalizing either way, so on-device /255 of the
    shipped bytes reproduces the float batch bit for bit at 1/4 the
    host->device traffic."""
    from fedcrack_tpu.data import as_model_batch

    pytest.importorskip("cv2")  # without cv2 the dataset degrades to float32
    image_dir, mask_dir = fixture_dirs
    pairs = list_pairs(image_dir, mask_dir)
    f32 = CrackDataset(pairs, img_size=64, batch_size=4, shuffle=False,
                       num_workers=0)
    u8 = CrackDataset(pairs, img_size=64, batch_size=4, shuffle=False,
                      num_workers=0, transport_dtype="uint8")
    for (fi, fm), (ui, um) in zip(f32, u8):
        assert ui.dtype == np.uint8 and um.dtype == np.uint8
        assert ui.nbytes == fi.nbytes // 4
        ni, nm = as_model_batch(ui, um)
        np.testing.assert_array_equal(np.asarray(ni), fi)
        np.testing.assert_array_equal(np.asarray(nm), fm)


def test_uint8_transport_without_cv2(fixture_dirs, monkeypatch):
    """The 1/4-staging-bytes property must hold with OpenCV absent: the PIL
    path decodes uint8 transport via the native uint8-domain resize instead
    of silently degrading to float32."""
    from fedcrack_tpu.data import as_model_batch, pipeline

    monkeypatch.setattr(pipeline, "_CV2", None)
    monkeypatch.setattr(pipeline, "_CV2_PROBED", True)
    image_dir, mask_dir = fixture_dirs
    pairs = list_pairs(image_dir, mask_dir)
    f32 = CrackDataset(pairs, img_size=64, batch_size=4, shuffle=False,
                       num_workers=0)
    u8 = CrackDataset(pairs, img_size=64, batch_size=4, shuffle=False,
                      num_workers=0, transport_dtype="uint8")
    assert u8.transport_dtype == "uint8"  # no silent downgrade
    for (fi, fm), (ui, um) in zip(f32, u8):
        assert ui.dtype == np.uint8 and um.dtype == np.uint8
        assert ui.nbytes == fi.nbytes // 4
        ni, nm = as_model_batch(ui, um)
        # the float path interpolates in float; uint8 transport quantizes to
        # the nearest uint8 step — within half a step after /255
        np.testing.assert_allclose(np.asarray(ni), fi, atol=0.5 / 255.0 + 1e-6)
        # mask labels are bit-identical across transport dtypes
        np.testing.assert_array_equal(np.asarray(nm), fm)


def test_train_and_eval_steps_accept_uint8_batches():
    """A uint8 transport batch must train/evaluate the same as its float32
    equivalent — normalization happens inside the jitted step. The staged
    VALUES are bit-identical (previous test); the uint8 step is a different
    XLA program, so outputs carry the usual program-to-program
    reduction-order noise (same tolerance class as the repo's mesh-vs-host
    golden tests), nothing more."""
    import jax
    import jax.numpy as jnp

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.train.local import create_train_state, eval_step, train_step

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    rng = np.random.default_rng(3)
    img_u8 = rng.integers(0, 256, (4, 16, 16, 3), np.uint8)
    msk_u8 = (rng.random((4, 16, 16, 1)) > 0.8).astype(np.uint8)
    img_f32 = img_u8.astype(np.float32) * np.float32(1.0 / 255.0)
    msk_f32 = msk_u8.astype(np.float32)

    state = create_train_state(jax.random.key(0), tiny)
    mu = jnp.float32(0.0)
    s_f, m_f = train_step(state, (img_f32, msk_f32), state.params, mu)
    s_u, m_u = train_step(state, (img_u8, msk_u8), state.params, mu)
    assert float(m_f["loss"]) == pytest.approx(float(m_u["loss"]), rel=1e-5)
    # One Adam step at lr=1e-3: any leaf can move at most ~lr, and for
    # zero-gradient leaves (BN-shadowed biases) reassociation noise flips
    # the step sign — so the bound is ~2*lr, not exactness.
    for a, b in zip(
        jax.tree_util.tree_leaves(s_f.params), jax.tree_util.tree_leaves(s_u.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2.5e-3)

    e_f = eval_step(state, (img_f32, msk_f32))
    e_u = eval_step(state, (img_u8, msk_u8))
    assert float(e_f["loss"]) == pytest.approx(float(e_u["loss"]), rel=1e-5)
    assert float(e_f["iou_inter"]) == pytest.approx(float(e_u["iou_inter"]), abs=1.0)


def test_to_uint8_transport_matches_decode_contract():
    """The shared synthetic-data uint8 encoder (bench + refscale tool) must
    be the exact inverse of the on-device normalization: u8 = rint(f32*255),
    masks {0,1} preserved — so uint8 staging of synthetic data keeps the
    bit-exact round-trip the file-decode path guarantees."""
    from fedcrack_tpu.data.pipeline import normalize_images, to_uint8_transport

    rng = np.random.default_rng(0)
    images = rng.uniform(0.0, 1.0, size=(4, 8, 8, 3)).astype(np.float32)
    masks = (rng.uniform(size=(4, 8, 8, 1)) > 0.5).astype(np.float32)
    u8i, u8m = to_uint8_transport(images, masks)
    assert u8i.dtype == np.uint8 and u8m.dtype == np.uint8
    np.testing.assert_array_equal(u8i, np.rint(images * 255.0).astype(np.uint8))
    np.testing.assert_array_equal(u8m.astype(np.float32), masks)
    # Round-trip through the on-device normalization: bit-exact u8 * (1/255)
    # (NOT u8/255.0 — the multiply-by-reciprocal differs from true division
    # by 1 ulp for ~half the byte values, and the multiply is the contract).
    back = np.asarray(normalize_images(u8i))
    np.testing.assert_array_equal(back, u8i.astype(np.float32) * np.float32(1.0 / 255.0))
