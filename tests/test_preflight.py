"""Flagship-shape mesh pre-flight (VERDICT.md round-1 item 6).

Every other mesh test uses a tiny model config for CI speed; these two compile
and execute the round at the shapes the north star actually names
(BASELINE.md config 3: 8 clients, full-width U-Net, 128/256 px crops), so
per-chip memory layouts and halo geometry are exercised on the 8-device
virtual mesh before real multi-chip hardware ever appears.
"""

import jax
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.parallel import (
    build_federated_round,
    build_spatial_federated_round,
    make_mesh,
    stack_client_data,
)
from fedcrack_tpu.train.local import create_train_state

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual mesh"
)


@pytest.mark.slow
def test_full_128px_resunet_round_on_8_device_mesh():
    """One step of the FULL flagship U-Net (default widths, 128x128) as a
    federated round over all 8 devices: 4 clients x 2-way intra-client DP."""
    config = ModelConfig()  # full feature widths, 128x128x3
    mesh = make_mesh(4, 2)
    steps, batch = 1, 2  # per-step batch splits over the batch axis
    per_client = [
        synth_crack_batch(steps * batch, img_size=config.img_size, seed=i)
        for i in range(4)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    variables = create_train_state(jax.random.key(0), config).variables
    round_fn = build_federated_round(mesh, config, learning_rate=1e-3, local_epochs=1)
    active = np.ones(4, np.float32)
    n_samples = np.full(4, float(steps * batch), np.float32)

    new_variables, metrics = round_fn(variables, images, masks, active, n_samples)
    jax.block_until_ready(new_variables)

    losses = np.asarray(metrics["loss"])
    assert losses.shape == (4,)
    assert np.all(np.isfinite(losses))
    # The round must actually update the global model.
    before = jax.tree_util.tree_leaves(variables["params"])[1]
    after = jax.tree_util.tree_leaves(new_variables["params"])[1]
    assert not np.allclose(np.asarray(before), np.asarray(after))


@pytest.mark.slow
def test_256px_spatial_federated_round_on_8_device_mesh():
    """Config 3's 256 px crop: 4 clients x 2-way spatial sharding (halo
    exchange + sync-BN), full-width U-Net — the composition for crops too
    large for one chip per client."""
    config = ModelConfig(img_size=256)
    mesh = make_mesh(4, 2, axis_names=("clients", "space"))
    steps, batch = 1, 1
    per_client = [
        synth_crack_batch(steps * batch, img_size=256, seed=10 + i) for i in range(4)
    ]
    images, masks = stack_client_data(per_client, steps, batch)
    variables = create_train_state(jax.random.key(1), config).variables
    round_fn = build_spatial_federated_round(
        mesh, config, learning_rate=1e-3, local_epochs=1
    )
    active = np.ones(4, np.float32)
    n_samples = np.full(4, float(steps * batch), np.float32)

    new_variables, metrics = round_fn(variables, images, masks, active, n_samples)
    jax.block_until_ready(new_variables)

    losses = np.asarray(metrics["loss"])
    assert losses.shape == (4,)
    assert np.all(np.isfinite(losses))
    iou = np.asarray(metrics["iou"])
    assert np.all((iou >= 0.0) & (iou <= 1.0))
