"""Seeded chaos scenarios: every hardened failure mode, exercised for real.

Each scenario injects a deterministic fault plan (fedcrack_tpu.chaos) into a
live in-process federation — transport plane (gRPC server + client threads)
or mesh plane (run_mesh_federation) — and must terminate within a bounded
wall clock with either a completed federation or a clean recorded abort.
Zero hangs is the point: the reference system's collect barrier hung
forever on the FIRST dead client (fl_server.py, SURVEY.md §2.4).

Covered fault types (ISSUE 3 acceptance: >= 8, both planes):
transport — crash before/during/after upload, straggler past the quorum,
network flap, corrupt payload, truncated payload, NaN payload, stale-round
replay, mid-round server kill-and-restart; mesh — injected device failure,
injected non-finite round output. Plus the torn-write (kill between write
and rename) sweep for every atomic persistence site.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from fedcrack_tpu.chaos import (
    CRASH_AFTER_UPLOAD,
    CRASH_BEFORE_UPLOAD,
    CRASH_DURING_UPLOAD,
    CORRUPT_PAYLOAD,
    NAN_UPDATE,
    NETWORK_FLAP,
    STALE_REPLAY,
    STRAGGLER_DELAY,
    TRUNCATE_PAYLOAD,
    ClientChaos,
    Fault,
    FaultPlan,
    InjectedCrash,
)
from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.transport import FedClient, FedServer
from fedcrack_tpu.transport.service import ServerThread

pytestmark = pytest.mark.chaos

# Every scenario must finish WELL inside this; a hang fails loudly instead
# of eating the suite's budget.
JOIN_S = 60


def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _fake_train(increment: float, samples: int):
    def train_fn(blob: bytes, rnd: int):
        tree = tree_from_bytes(blob)
        tree["params"]["w"] = tree["params"]["w"] + increment
        return tree_to_bytes(tree), samples, {"loss": float(rnd)}

    return train_fn


@pytest.fixture
def cfg():
    return FedConfig(
        max_rounds=3,
        cohort_size=2,
        registration_window_s=5.0,
        poll_period_s=0.05,
        # 2.5 s, not 0.5: the deadline only exists to drop the DEAD client.
        # At 0.5 s this host's ~0.5-1 s ambient scheduler stalls (2 cores, 8
        # spin-waiting virtual devices) raced the SURVIVOR's round-trip into
        # the shrink — the same pathology the r12 flake fix widened
        # test_transport's dead-client deadline for (reproduced 3/3 under
        # load at r13; the scenarios that want a deadline that never fires
        # already override to 30 s).
        round_deadline_s=2.5,
        host="127.0.0.1",
        port=0,
    )


def _run_clients(clients, keys=None):
    """Run sessions on threads; return {key: SessionResult | Exception}.
    Bounded join — a hung scenario is an assertion, not a stuck suite."""
    keys = keys or [c.cname for c in clients]
    res = {}

    def run(c, key):
        try:
            res[key] = c.run_session()
        except Exception as e:  # noqa: BLE001 — the exception IS the result
            res[key] = e

    threads = [
        threading.Thread(target=run, args=(c, k)) for c, k in zip(clients, keys)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=JOIN_S)
        assert not t.is_alive(), "scenario hung past the wall-clock bound"
    res["_wall_s"] = time.monotonic() - t0
    return res


def _chaos_client(cfg, port, cname, faults, train=None, **kw):
    return FedClient(
        cfg,
        train or _fake_train(1.0, 10),
        cname=cname,
        port=port,
        chaos=ClientChaos(FaultPlan(faults)),
        **kw,
    )


# ---------- transport plane: client crash phases ----------


def test_crash_before_upload_deadline_rescues(cfg):
    """The client dies before its round-2 report ever reaches the server;
    the deadline shrinks the cohort and the survivor finishes alone."""
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        a = FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        b = _chaos_client(
            cfg, st.port, "b", [Fault(CRASH_BEFORE_UPLOAD, round=2, client="b")]
        )
        res = _run_clients([a, b])
        state = st.state
    assert isinstance(res["b"], InjectedCrash)
    assert res["a"].rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    assert state.cohort == frozenset({"a"})
    # b's round-1 update DID count before the crash.
    assert state.history[0]["clients"] == ["a", "b"]


@pytest.mark.parametrize("kind", [CRASH_DURING_UPLOAD, CRASH_AFTER_UPLOAD])
def test_crash_around_upload_restart_rejoins(cfg, kind):
    """The client dies with its round-1 update already ON the server (during:
    before it saw the reply; after: on its next call). Its restart under the
    same cname re-enrolls (SW resync), the pre-crash report is dropped, and
    the full cohort finishes — no deadline shrink."""
    cfg = dataclasses.replace(cfg, round_deadline_s=30.0)  # recovery, not shrink
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        a = FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        b1 = _chaos_client(cfg, st.port, "b", [Fault(kind, round=1, client="b")])
        res = {}

        def run(c, key):
            try:
                res[key] = c.run_session()
            except Exception as e:
                res[key] = e

        # a's session blocks on b's recovery, so b1 is joined FIRST and the
        # restart happens while a is still polling.
        ta = threading.Thread(target=run, args=(a, "a"))
        tb = threading.Thread(target=run, args=(b1, "b1"))
        ta.start()
        tb.start()
        tb.join(JOIN_S)
        assert not tb.is_alive(), "crashing client hung"
        assert isinstance(res["b1"], InjectedCrash)
        b2 = FedClient(cfg, _fake_train(1.0, 10), cname="b", port=st.port)
        r_b2 = b2.run_session()
        ta.join(JOIN_S)
        assert not ta.is_alive(), "surviving client hung"
        state = st.state
    assert not isinstance(res["a"], Exception), res["a"]
    assert r_b2.enrolled, "restarted cohort member was locked out"
    assert r_b2.rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    assert state.cohort == frozenset({"a", "b"})
    assert [h["round"] for h in state.history] == [1, 2, 3]
    assert all(h["clients"] == ["a", "b"] for h in state.history)


# ---------- transport plane: quorum + straggler ----------


def test_quorum_closes_round_and_straggler_resyncs(cfg):
    """3-client cohort, quorum 2/3: a straggler sleeping past the quorum
    close must NOT stall the round; its late report is resynced (never
    averaged) and it rejoins the next round."""
    cfg = dataclasses.replace(
        cfg,
        cohort_size=3,
        quorum_fraction=2.0 / 3.0,
        round_deadline_s=30.0,  # quorum, not the deadline, must close rounds
        max_rounds=2,
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        fast = [
            FedClient(cfg, _fake_train(1.0, 8), cname=n, port=st.port)
            for n in ("a", "b")
        ]
        slow = _chaos_client(
            cfg,
            st.port,
            "c",
            [Fault(STRAGGLER_DELAY, round=1, client="c", delay_s=1.0)],
            train=_fake_train(5.0, 8),
        )
        res = _run_clients(fast + [slow])
        state = st.state
    for n in ("a", "b", "c"):
        assert not isinstance(res[n], Exception), res[n]
    assert res["a"].rounds_completed == 2 and res["b"].rounds_completed == 2
    # The straggler ends the session holding the final weights (via FIN or a
    # resync) — never dead, never hung.
    assert res["c"].enrolled and res["c"].final_weights is not None
    assert state.phase == R.PHASE_FINISHED
    h1 = state.history[0]
    assert h1["quorum"] == 2 and h1["cohort_size"] == 3
    # Round 1 aggregated WITHOUT the straggler — the quorum closed it while
    # c slept, and c's late +5.0 update never entered any average: round-1
    # weights are exactly the fast clients' +1.0 math.
    assert h1["clients"] == ["a", "b"]
    for h in state.history:
        assert "c" not in h["clients"] or h["round"] > 1
    final = tree_from_bytes(state.global_blob)["params"]["w"]
    assert np.all(np.isfinite(final))


# ---------- transport plane: poisoned payloads ----------


@pytest.mark.parametrize(
    "kind,reason_frag",
    [
        (CORRUPT_PAYLOAD, "undecodable"),
        (TRUNCATE_PAYLOAD, "undecodable"),
        (NAN_UPDATE, "non-finite"),
    ],
)
def test_poisoned_update_rejected_and_never_averaged(cfg, kind, reason_frag):
    """A corrupt/truncated/NaN round-2 payload is REJECTED by sanitation
    (the poisoned client fails loudly), the federation completes via the
    deadline shrink, and the global average stays exactly the clean
    clients' math — the poison never touches FedAvg."""
    # The poisoned upload must REACH the sanitation gate to draw the
    # rejection under test. On a loaded host a deadline shrink can drop b
    # from the cohort before its upload lands, and the server answers
    # 'not in cohort' instead — a different (also-correct) rejection that
    # proves nothing about sanitation. No finite deadline outruns an
    # arbitrary scheduler stall (0.5 s raced at ~1.4x ambient suite load;
    # 8 s still raced under an adversarial 8-core burn), so the benign
    # race is detected and the scenario retried instead: the enroll
    # window is widened (free — enrollment closes early once both clients
    # arrive) and the deadline kept short (it paces round 2's shrink
    # after b dies, so every widening second is 3x wall in tier-1).
    cfg = dataclasses.replace(cfg, registration_window_s=30.0)
    for attempt in range(3):
        server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
        with ServerThread(server) as st:
            a = FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port)
            b = _chaos_client(
                cfg, st.port, "b", [Fault(kind, round=2, client="b")],
                train=_fake_train(3.0, 10),
            )
            res = _run_clients([a, b])
            state = st.state
        if not (
            attempt < 2
            and isinstance(res["b"], RuntimeError)
            and "not in cohort" in str(res["b"])
        ):
            break
    assert isinstance(res["b"], RuntimeError)  # "server rejected update"
    assert "update rejected" in str(res["b"])
    assert res["a"].rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    # Round 1: both (w + (1+3)/2 = 2); rounds 2-3: a alone (+1 each).
    final = tree_from_bytes(state.global_blob)
    np.testing.assert_allclose(final["params"]["w"], 2.0 + 1.0 + 1.0, atol=1e-5)
    rejected = {k: v for h in state.history for k, v in h["rejected"].items()}
    assert "b" in rejected and reason_frag in rejected["b"]


def test_stale_replay_resynced_never_averaged(cfg):
    """A replayed round-(r-1) report: the server re-syncs the sender to the
    current round instead of averaging the stale blob or killing the
    client; the federation completes with exact math."""
    cfg = dataclasses.replace(cfg, round_deadline_s=30.0)
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        a = FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        # b's round-2 report is re-tagged as round 1 (a replay); b then
        # resyncs and redoes round 2.
        b = _chaos_client(
            cfg, st.port, "b", [Fault(STALE_REPLAY, round=2, client="b")],
            train=_fake_train(1.0, 10),
        )
        res = _run_clients([a, b])
        state = st.state
    assert not isinstance(res["a"], Exception), res["a"]
    assert not isinstance(res["b"], Exception), res["b"]
    assert res["a"].rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    assert [h["round"] for h in state.history] == [1, 2, 3]
    # The replay was logged against the round it intruded on.
    assert any(
        "stale round" in h["rejected"].get("b", "") for h in state.history
    )
    # Every round's average is exact: +1 per round from each reporter.
    final = tree_from_bytes(state.global_blob)
    np.testing.assert_allclose(final["params"]["w"], 3.0, atol=1e-5)


# ---------- transport plane: network flap ----------


def test_network_flap_ridden_out_by_retries(cfg):
    """Two consecutive injected UNAVAILABLEs on round 2's calls: the
    jittered backoff schedule must ride them out with zero protocol
    damage — full cohort, every round, exact average."""
    cfg = dataclasses.replace(cfg, round_deadline_s=30.0)  # retries, not shrink
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        a = FedClient(cfg, _fake_train(1.0, 10), cname="a", port=st.port)
        b = _chaos_client(
            cfg, st.port, "b",
            [Fault(NETWORK_FLAP, round=2, client="b", count=2)],
        )
        res = _run_clients([a, b])
        state = st.state
    assert not isinstance(res["b"], Exception), res["b"]
    assert res["a"].rounds_completed == 3 and res["b"].rounds_completed == 3
    assert state.phase == R.PHASE_FINISHED
    assert all(h["clients"] == ["a", "b"] for h in state.history)


def test_retry_budget_and_nonretryable_codes():
    """Satellite audit pins: a non-retryable code surfaces immediately (one
    attempt, no schedule burn); the per-call retry budget caps total
    retry wall-clock even when max_retries would allow more."""
    import grpc

    from fedcrack_tpu.transport.client import NON_RETRYABLE_CODES

    class FakeErr(grpc.RpcError):
        def __init__(self, code):
            self._code = code

        def code(self):
            return self._code

    calls = {"n": 0}

    def failing_method(it, timeout=None, wait_for_ready=None):
        calls["n"] += 1
        raise FakeErr(failing_method.code)

    cfg = FedConfig(port=0)
    client = FedClient(cfg, _fake_train(1.0, 1), cname="x", max_retries=5)

    assert grpc.StatusCode.INVALID_ARGUMENT in NON_RETRYABLE_CODES
    failing_method.code = grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError):
        client._call(failing_method, object())
    assert calls["n"] == 1, "non-retryable code must not be retried"

    # Retryable code: the whole schedule runs (bounded by max_retries)...
    calls["n"] = 0
    failing_method.code = grpc.StatusCode.UNAVAILABLE
    short = FedClient(cfg, _fake_train(1.0, 1), cname="x", max_retries=3)
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError):
        short._call(failing_method, object())
    assert calls["n"] == 3
    # ...with jittered exponential backoff: strictly positive, bounded.
    assert 0.1 < time.monotonic() - t0 < 10.0

    # Budget cap: a tiny budget stops retrying long before max_retries.
    calls["n"] = 0
    tight = FedClient(
        cfg, _fake_train(1.0, 1), cname="x", max_retries=50, retry_budget_s=0.3
    )
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError):
        tight._call(failing_method, object())
    assert time.monotonic() - t0 < 5.0
    assert calls["n"] < 50


# ---------- transport plane: mid-round server kill-and-restart ----------


def test_server_kill_restart_resumes_same_round(tmp_path, cfg):
    """THE tentpole scenario: the server dies after 1 of 2 round-2 updates
    landed; the restart resumes the SAME round with the received update
    intact (identical history prefix), and the federation completes with
    the exact trajectory an unkilled server would have produced."""
    cfg = dataclasses.replace(
        cfg,
        round_deadline_s=30.0,
        state_path=str(tmp_path / "server_state.msgpack"),
    )
    from fedcrack_tpu.ckpt import load_state_file

    import grpc

    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import METHOD, SERVICE_NAME

    def caller(port):
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        method = channel.stream_stream(
            f"/{SERVICE_NAME}/{METHOD}",
            request_serializer=pb.ClientMessage.SerializeToString,
            response_deserializer=pb.ServerMessage.FromString,
        )
        return channel, lambda m: next(
            iter(method(iter([m]), timeout=10, wait_for_ready=True))
        )

    def ready(cname):
        m = pb.ClientMessage(cname=cname)
        m.ready.SetInParent()
        return m

    def done(cname, rnd, val, ns):
        m = pb.ClientMessage(cname=cname)
        m.done.round = rnd
        m.done.weights = tree_to_bytes(_vars(val))
        m.done.sample_count = ns
        return m

    server1 = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server1) as st1:
        ch, call = caller(st1.port)
        assert call(ready("a")).status == R.SW
        assert call(ready("b")).status == R.SW
        # Round 1 completes cleanly.
        assert call(done("a", 1, 1.0, 10)).status == R.RESP_ACY
        assert call(done("b", 1, 3.0, 30)).status == R.RESP_ARY
        history_prefix = [dict(h) for h in st1.state.history]
        # Round 2: only a reports, then the server dies.
        assert call(done("a", 2, 2.0, 10)).status == R.RESP_ACY
        ch.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = load_state_file(cfg.state_path, cfg)
            if s is not None and "a" in s.received and s.current_round == 2:
                break
            time.sleep(0.01)
        else:
            pytest.fail("statefile never captured the mid-round update")
        st1.kill()

    server2 = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    # SAME round, same cohort, a's update still held; history prefix intact.
    assert server2.state.phase == R.PHASE_RUNNING
    assert server2.state.current_round == 2
    assert server2.state.cohort == frozenset({"a", "b"})
    assert set(server2.state.received) == {"a"}
    assert [dict(h) for h in server2.state.history] == history_prefix

    with ServerThread(server2) as st2:
        ch, call = caller(st2.port)
        rep = call(done("b", 2, 4.0, 30))
        assert rep.status == R.RESP_ARY
        # The aggregation used a's DISK-RESTORED update:
        # (10*2 + 30*4) / 40 = 3.5 — bit-for-bit what no kill would give.
        got = tree_from_bytes(rep.weights)["params"]["w"]
        np.testing.assert_allclose(got, 3.5, atol=1e-6)
        # Round 3 completes the federation.
        call(done("a", 3, 1.0, 10))
        assert call(done("b", 3, 1.0, 30)).status == R.FIN
        ch.close()
        state = st2.state
    assert state.phase == R.PHASE_FINISHED
    assert [h["round"] for h in state.history] == [1, 2, 3]
    assert state.history[0] == history_prefix[0]


def test_server_kill_restart_with_live_clients(tmp_path, cfg):
    """Same kill, but with real FedClient threads mid-flight: their jittered
    retries must carry them across the restart (same port) and the
    federation completes without losing a round."""
    server_state = str(tmp_path / "server_state.msgpack")
    cfg = dataclasses.replace(
        cfg, round_deadline_s=30.0, state_path=server_state, max_rounds=2
    )

    slow_gate = threading.Event()
    reported = threading.Event()

    def train_a(blob, rnd):
        return _fake_train(1.0, 10)(blob, rnd)

    def train_b(blob, rnd):
        if rnd == 2:
            reported.set()          # b is about to report round 2...
            slow_gate.wait(JOIN_S)  # ...but waits until the restart happened
        return _fake_train(3.0, 30)(blob, rnd)

    server1 = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    st1 = ServerThread(server1)
    st1.__enter__()
    port = st1.port
    try:
        a = FedClient(cfg, train_a, cname="a", port=port)
        b = FedClient(cfg, train_b, cname="b", port=port)
        res = {}

        def run(c, key):
            try:
                res[key] = c.run_session()
            except Exception as e:
                res[key] = e

        ta = threading.Thread(target=run, args=(a, "a"))
        tb = threading.Thread(target=run, args=(b, "b"))
        ta.start()
        tb.start()
        # Wait until round 1 closed and a's round-2 update is durable.
        from fedcrack_tpu.ckpt import load_state_file

        deadline = time.monotonic() + JOIN_S
        while time.monotonic() < deadline:
            s = load_state_file(server_state, cfg)
            if (
                s is not None
                and s.current_round == 2
                and "a" in s.received
                and reported.is_set()
            ):
                break
            time.sleep(0.01)
        else:
            pytest.fail("never reached the mid-round kill point")
        st1.kill()

        # Restart on the SAME port (the clients keep dialing it).
        server2 = FedServer(
            dataclasses.replace(cfg, port=port), _vars(0.0), tick_period_s=0.05
        )
        assert server2.state.current_round == 2
        assert set(server2.state.received) == {"a"}
        with ServerThread(server2) as st2:
            slow_gate.set()
            ta.join(JOIN_S)
            tb.join(JOIN_S)
            assert not ta.is_alive() and not tb.is_alive(), "clients hung"
            state = st2.state
    finally:
        slow_gate.set()
        st1.kill()  # no-op if already killed

    assert not isinstance(res["a"], Exception), res["a"]
    assert not isinstance(res["b"], Exception), res["b"]
    assert state.phase == R.PHASE_FINISHED
    assert [h["round"] for h in state.history] == [1, 2]
    # Round 2 averaged a's pre-kill update with b's post-restart one:
    # round 1 -> w=2.5; round 2 -> (10*3.5 + 30*5.5)/40 = 5.0.
    final = tree_from_bytes(state.global_blob)
    np.testing.assert_allclose(final["params"]["w"], 5.0, atol=1e-5)


# ---------- torn-write safety (satellite) ----------


def test_statefile_kill_between_write_and_rename(tmp_path, cfg):
    """A crash between temp-write and rename must leave the PREVIOUS
    snapshot fully readable — the stranded temp file is ignored."""
    from fedcrack_tpu.ckpt import load_state_file, save_state_file

    cfg = dataclasses.replace(cfg, state_path=str(tmp_path / "state.msgpack"))
    state = R.initial_state(cfg, _vars(0.0))
    state, _ = R.transition(state, R.Ready("a", now=0.0))
    save_state_file(cfg.state_path, state)

    # Simulate the kill: the NEXT snapshot's temp file exists (garbage),
    # the rename never happened.
    import os

    with open(f"{cfg.state_path}.tmp.{os.getpid()}", "wb") as f:
        f.write(b"\x00garbage: killed before rename")

    restored = load_state_file(cfg.state_path, cfg)
    assert restored is not None
    assert restored.cohort == frozenset({"a"})

    # And an interrupted atomic_write_bytes (rename raising) leaves the
    # original intact.
    from fedcrack_tpu import ioutils

    real_replace = os.replace

    def exploding_replace(src, dst):
        raise OSError("injected kill at rename")

    os.replace = exploding_replace
    try:
        with pytest.raises(OSError):
            ioutils.atomic_write_bytes(cfg.state_path, b"new bytes")
    finally:
        os.replace = real_replace
    assert load_state_file(cfg.state_path, cfg).cohort == frozenset({"a"})


def test_write_best_torn_pair_detected(tmp_path):
    """_write_best's two-rename pair: a kill between the model rename and
    the sidecar rename is detected by the sha256 binding and the torn pair
    is ignored on the next boot (existing semantics, now through the
    fsync'd atomic writer)."""
    import json

    from fedcrack_tpu.transport.service import _load_best, _write_best

    best = tmp_path / "best.msgpack"
    _write_best(str(best), b"model-v1", {"loss": 0.5, "round": 1})
    assert _load_best(str(best))["loss"] == 0.5

    # Kill between the renames: model file updated, sidecar still v1.
    from fedcrack_tpu.ioutils import atomic_write_bytes

    atomic_write_bytes(str(best), b"model-v2")
    assert _load_best(str(best)) is None  # hash mismatch -> torn pair ignored
    side = json.loads((tmp_path / "best.msgpack.json").read_text())
    assert side["loss"] == 0.5  # the stale sidecar itself is intact


# ---------- mesh plane ----------


TINY_KW = dict(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)


@pytest.fixture(scope="module")
def mesh_setup():
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(**TINY_KW)
    steps, batch, n_clients = 2, 4, 2
    mesh = make_mesh(n_clients, 1)
    round_fn = build_federated_round(mesh, tiny, learning_rate=1e-3, local_epochs=1)

    def data_fn(r):
        per_client = [
            synth_crack_batch(steps * batch, img_size=16, seed=10 * r + i)
            for i in range(n_clients)
        ]
        images, masks = stack_client_data(per_client, steps, batch)
        active = np.ones(n_clients, np.float32)
        n_samples = np.full(n_clients, float(steps * batch), np.float32)
        return images, masks, active, n_samples

    def init_vars():
        return create_train_state(jax.random.key(0), tiny).variables

    return round_fn, mesh, data_fn, init_vars


@pytest.fixture(scope="module")
def clean_two_rounds(mesh_setup):
    """The unfaulted 2-round reference trajectory both replay tests pin
    against (computed once — the clean run is the expensive part)."""
    from fedcrack_tpu.parallel import run_mesh_federation

    round_fn, mesh, data_fn, init_vars = mesh_setup
    v_clean, _ = run_mesh_federation(round_fn, init_vars(), data_fn, 2, mesh)
    import jax

    return jax.device_get(v_clean)


def _assert_trees_equal(got, want):
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mesh_kill_and_replay_bit_identical(mesh_setup, clean_two_rounds):
    """Acceptance pin: device failure at round 0 + NaN corruption at round 1,
    each absorbed by one replay — the final weights are BIT-identical to
    the unfaulted run, and the records say exactly what happened."""
    from fedcrack_tpu.chaos import MESH_DEVICE_FAIL, MESH_NONFINITE, MeshChaos
    from fedcrack_tpu.parallel import run_mesh_federation

    round_fn, mesh, data_fn, init_vars = mesh_setup
    plan = FaultPlan(
        [Fault(MESH_DEVICE_FAIL, round=0), Fault(MESH_NONFINITE, round=1)]
    )
    v_chaos, records = run_mesh_federation(
        round_fn,
        init_vars(),
        data_fn,
        2,
        mesh,
        max_round_retries=2,
        fault_injector=MeshChaos(plan),
    )
    _assert_trees_equal(v_chaos, clean_two_rounds)
    assert [r.retries for r in records] == [1, 1]
    assert "InjectedDeviceFailure" in records[0].faults[0]
    assert "NonFiniteRound" in records[1].faults[0]
    assert not plan.pending  # every scheduled fault actually fired


def test_mesh_checkpointer_backed_replay(mesh_setup, clean_two_rounds, tmp_path):
    """With a FedCheckpointer attached, the replay restores from the durable
    round boundary (not just the in-memory snapshot) and the trajectory
    stays identical; the checkpoint itself remains resumable."""
    from fedcrack_tpu.chaos import MESH_DEVICE_FAIL, MeshChaos
    from fedcrack_tpu.ckpt import FedCheckpointer
    from fedcrack_tpu.parallel import run_mesh_federation

    round_fn, mesh, data_fn, init_vars = mesh_setup
    plan = FaultPlan([Fault(MESH_DEVICE_FAIL, round=1)])
    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        v_chaos, records = run_mesh_federation(
            round_fn,
            init_vars(),
            data_fn,
            2,
            mesh,
            checkpointer=ckptr,
            max_round_retries=1,
            fault_injector=MeshChaos(plan),
        )
        assert ckptr.latest_version() == 2  # both boundaries checkpointed
    _assert_trees_equal(v_chaos, clean_two_rounds)
    assert records[1].retries == 1


def test_mesh_retries_exhausted_aborts_cleanly(mesh_setup):
    """More injected failures than the retry bound: a clean, recorded abort
    (the exception names the fault) — never a hang, never NaN weights
    silently returned."""
    from fedcrack_tpu.chaos import MESH_DEVICE_FAIL, MeshChaos
    from fedcrack_tpu.chaos.inject import InjectedDeviceFailure
    from fedcrack_tpu.parallel import run_mesh_federation

    round_fn, mesh, data_fn, init_vars = mesh_setup
    plan = FaultPlan(
        [Fault(MESH_DEVICE_FAIL, round=0), Fault(MESH_DEVICE_FAIL, round=0)]
    )
    with pytest.raises(InjectedDeviceFailure):
        run_mesh_federation(
            round_fn,
            init_vars(),
            data_fn,
            1,
            mesh,
            max_round_retries=1,
            fault_injector=MeshChaos(plan),
        )


# ---------- the long-horizon soak (excluded from tier-1) ----------


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_soak_random_fault_schedule(seed, tmp_path):
    """Many rounds x a seeded random fault schedule over a 3-client cohort:
    the federation must terminate (complete or cleanly aborted) within the
    bound, with gapless history and only sanitation-rejected updates
    missing. Replayable: the failing seed IS the repro."""
    from fedcrack_tpu.chaos import CLIENT_KINDS

    cfg = FedConfig(
        max_rounds=6,
        cohort_size=3,
        registration_window_s=5.0,
        poll_period_s=0.05,
        round_deadline_s=1.5,
        quorum_fraction=2.0 / 3.0,
        port=0,
        state_path=str(tmp_path / f"soak_{seed}.msgpack"),
    )
    names = ["a", "b", "c"]
    plan = FaultPlan.generate(
        seed,
        n_rounds=cfg.max_rounds,
        clients=names,
        kinds=sorted(CLIENT_KINDS),
        n_faults=4,
        max_delay_s=0.4,
    )
    # Each client consumes only ITS faults — one hook per thread, no shared
    # mutable plan across threads.
    per_client = {
        n: FaultPlan([f for f in plan.pending if f.client == n]) for n in names
    }
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        clients = [
            FedClient(
                cfg,
                _fake_train(1.0 + i, 10),
                cname=n,
                port=st.port,
                chaos=ClientChaos(per_client[n]),
            )
            for i, n in enumerate(names)
        ]
        res = _run_clients(clients)
        # Crashed clients restart once, like operators restart pods.
        for n in names:
            if isinstance(res[n], Exception):
                retry = FedClient(
                    cfg, _fake_train(1.0, 10), cname=n, port=st.port
                )
                try:
                    retry.run_session()
                except Exception:
                    pass  # a second death is allowed; liveness is the server's
        deadline = time.monotonic() + JOIN_S
        while time.monotonic() < deadline and st.state.phase != R.PHASE_FINISHED:
            time.sleep(0.05)
        state = st.state
    assert state.phase == R.PHASE_FINISHED, (
        f"seed {seed}: federation did not terminate "
        f"(phase={state.phase}, round={state.current_round})"
    )
    rounds = [h["round"] for h in state.history]
    assert rounds == list(range(1, len(rounds) + 1)), f"gapped history: {rounds}"
