"""Round-16 distributed tracing: cross-process context propagation over a
real gRPC socket, stitched back into one chain by tools/trace_stitch.py.

The load-bearing claims:

- a client push's wire context (``"<trace>#<key>"`` in the TrainDone
  metrics map) is re-parented onto the root's ``fed.flush`` span, an edge
  re-parents its leaf offers onto its ``edge.flush_partial`` span and
  forwards its OWN context up, so the stitcher reconstructs the full
  ``client → edge → root → flush`` chain from the span JSONL;
- the whole chain shares ONE trace id (``fedtr-v<base>`` — derived from
  the in-band model version, no extra negotiation);
- a deliberately dropped/corrupted context degrades to a parentless span:
  the round closes normally, the flush simply links fewer parents, and
  nothing anywhere raises;
- the stitcher joins multiple per-process files (the deployment shape) and
  its CLI enforces chain completeness via its exit code.
"""

import dataclasses
import json
import threading

import numpy as np

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_to_bytes
from fedcrack_tpu.fed.tree import EdgeAggregator
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.tools.trace_stitch import load_records, stitch, stitch_files, summarize
from fedcrack_tpu.transport import FedClient, FedServer
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.codec import encode_scalar_map, event_from_message
from fedcrack_tpu.transport.edge import EdgeRelay, raw_caller
from fedcrack_tpu.transport.service import ServerThread


def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _trainer(delta: float):
    def train(blob, rnd):
        from fedcrack_tpu.fed.serialization import tree_from_bytes

        tree = tree_from_bytes(blob)
        tree["params"]["w"] = tree["params"]["w"] + delta
        return tree_to_bytes(tree), 4, {"loss": float(rnd)}

    return train


def test_event_from_message_extracts_and_degrades_trace_ctx():
    m = pb.ClientMessage(cname="c")
    m.done.round = 1
    m.done.weights = b"w"
    m.done.sample_count = 3
    encode_scalar_map(m.done.metrics, {"loss": 0.5, "__trace": "fedtr-v0#push:c:r1"})
    ev = event_from_message(m, 1.0)
    assert ev.trace_ctx == "fedtr-v0#push:c:r1"
    # A non-string __trace (a poisoned/corrupted scalar) degrades to "".
    m2 = pb.ClientMessage(cname="c")
    m2.done.round = 1
    m2.done.weights = b"w"
    m2.done.sample_count = 3
    encode_scalar_map(m2.done.metrics, {"__trace": 3.25})
    assert event_from_message(m2, 1.0).trace_ctx == ""
    # No context at all: the default, not an error.
    m3 = pb.ClientMessage(cname="c")
    m3.done.round = 1
    m3.done.weights = b"w"
    m3.done.sample_count = 3
    assert event_from_message(m3, 1.0).trace_ctx == ""


def test_trace_propagates_client_edge_root_over_grpc(tmp_path):
    """The satellite scenario: 2 FedClients + 1 edge shard (2 leaf offers
    relayed as one partial) against a real gRPC root; the stitched chain
    covers client→edge→root under the round's single trace id."""
    spans_path = tmp_path / "spans.jsonl"
    tracing.install(spans_path)
    cfg = FedConfig(
        max_rounds=1,
        cohort_size=3,
        registration_window_s=5.0,
        round_deadline_s=30.0,
        poll_period_s=0.05,
        port=0,
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    try:
        with ServerThread(server) as st:
            cfg_port = dataclasses.replace(cfg, port=st.port)
            clients = [
                FedClient(cfg_port, _trainer(0.1), cname="c0"),
                FedClient(cfg_port, _trainer(0.2), cname="c1"),
            ]
            results = {}
            threads = [
                threading.Thread(
                    target=lambda c=c: results.update({c.cname: c.run_session()})
                )
                for c in clients
            ]
            for t in threads:
                t.start()

            with EdgeRelay("edge-0", st.port) as relay:
                handshake = relay.enroll()
                base = relay.pull()
                edge = EdgeAggregator("edge-0", server.state.template)
                edge.begin_round(
                    int(handshake["current_round"]),
                    base,
                    int(handshake["model_version"]),
                    ["leaf-0", "leaf-1"],
                )
                for i, leaf in enumerate(("leaf-0", "leaf-1")):
                    ctx = tracing.TraceContext(
                        tracing.version_trace(edge.base_version),
                        f"train:{leaf}:r1",
                    )
                    with tracing.span(
                        "client.train", trace=ctx.trace, cname=leaf,
                        ctx=ctx.to_wire(),
                    ):
                        blob = tree_to_bytes(_vars(0.3 + i / 10))
                    ok, why = edge.offer(leaf, blob, 4, trace_ctx=ctx.to_wire())
                    assert ok, why
                partial, total = edge.partial()
                assert edge.last_partial_ctx.startswith("fedtr-v0#edge:edge-0:")
                status, _weights, _cfg = relay.push_partial(
                    1, partial, total, trace_ctx=edge.last_partial_ctx
                )
                assert status in (R.RESP_ACY, R.RESP_ARY, R.FIN)
            for t in threads:
                t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
    finally:
        tracing.uninstall()

    assert results["c0"].rounds_completed == 1
    stitched = stitch_files([str(spans_path)])
    assert stitched["n_chains"] == 1
    chain = stitched["chains"][0]
    assert chain["trace"] == "fedtr-v0" and chain["version"] == 1
    # All three uploads (2 clients + the edge partial) re-parented onto the
    # flush; the edge entry resolves down to its two leaf offers.
    assert len(chain["upstream"]) == 3
    assert chain["unresolved_links"] == []
    by_name = {}
    for u in chain["upstream"]:
        by_name.setdefault(u["span"]["name"], []).append(u)
    assert len(by_name["client.push"]) == 2
    (edge_entry,) = by_name["edge.flush_partial"]
    assert [leaf["name"] for leaf in edge_entry["leaves"]] == [
        "client.train", "client.train",
    ]
    # Local parentage: each push chains to its train span in-file.
    for push in by_name["client.push"]:
        assert push["train"] is not None
        assert push["train"]["name"] == "client.train"
    # Single trace id across every chain stage that exists (no serve plane
    # in this session, so the chain is upstream-only and not "complete").
    assert {"client", "edge", "fed"} <= set(chain["planes_crossed"])
    assert not chain["complete"]


def test_dropped_context_degrades_to_parentless_never_crashes(tmp_path):
    """A garbage __trace (malformed string round 1, then a push with no
    context round 2) must cost the sender its parentage, nothing else."""
    spans_path = tmp_path / "spans.jsonl"
    tracing.install(spans_path)
    cfg = FedConfig(
        max_rounds=2,
        cohort_size=1,
        registration_window_s=5.0,
        round_deadline_s=30.0,
        port=0,
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    try:
        with ServerThread(server) as st:
            channel, call = raw_caller(st.port)
            msg = pb.ClientMessage(cname="raw")
            msg.ready.SetInParent()
            assert call(msg).status == R.SW
            msg = pb.ClientMessage(cname="raw")
            msg.pull.SetInParent()
            base = call(msg).weights
            for rnd, garbage in ((1, "not a context"), (2, None)):
                msg = pb.ClientMessage(cname="raw")
                msg.done.round = rnd
                msg.done.weights = tree_to_bytes(_vars(0.5))
                msg.done.sample_count = 2
                if garbage is not None:
                    encode_scalar_map(msg.done.metrics, {"__trace": garbage})
                rep = call(msg)
                assert rep.status in (R.RESP_ARY, R.FIN)
            channel.close()
            assert base  # the pull really happened
    finally:
        tracing.uninstall()
    flushes = tracing.read_spans(spans_path, name="fed.flush")
    assert len(flushes) == 2
    for flush in flushes:
        assert flush["links"] == []  # parentless, by design


def _write_spans(path, records):
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def _synthetic_chain_files(tmp_path, *, break_stage=None):
    """Two per-process files (client vs server+serve) carrying one full
    lifecycle; ``break_stage`` drops a stage to make the chain incomplete."""
    trace = "fedtr-v4"
    client = [
        {"name": "client.train", "trace": trace, "span": 1, "parent": None,
         "t": 0.0, "dur_s": 0.5, "ctx": f"{trace}#train:c0:r5"},
        {"name": "client.push", "trace": trace, "span": 2, "parent": 1,
         "t": 0.5, "dur_s": 0.1, "ctx": f"{trace}#push:c0:r5"},
    ]
    serverside = [
        {"name": "fed.flush", "trace": trace, "span": 1, "parent": None,
         "t": 0.7, "dur_s": 0.0, "version": 5, "round": 5,
         "ctx": f"{trace}#flush:v5", "links": [f"{trace}#push:c0:r5"]},
        {"name": "serve.swap", "trace": trace, "span": 2, "parent": None,
         "t": 0.9, "dur_s": 0.02, "to_version": 5, "installed": True,
         "ctx": f"{trace}#swap:v5", "remote_parent": f"{trace}#flush:v5"},
        {"name": "serve.batch", "trace": trace, "span": 3, "parent": None,
         "t": 1.0, "dur_s": 0.01, "model_version": 5,
         "remote_parent": f"{trace}#swap:v5"},
    ]
    if break_stage is not None:
        serverside = [r for r in serverside if r["name"] != break_stage]
    a, b = tmp_path / "client.jsonl", tmp_path / "server.jsonl"
    _write_spans(a, client)
    _write_spans(b, serverside)
    return [str(a), str(b)]


def test_stitch_joins_per_process_files_into_a_complete_chain(tmp_path):
    paths = _synthetic_chain_files(tmp_path)
    stitched = stitch(load_records(paths))
    assert stitched["complete"] and stitched["n_complete"] == 1
    chain = stitched["best"]
    assert chain["trace"] == "fedtr-v4"
    assert chain["planes_crossed"] == ["client", "fed", "serve"]
    assert len(chain["files"]) == 2  # the chain really crossed files
    assert chain["upstream"][0]["train"]["name"] == "client.train"
    assert chain["swap"]["name"] == "serve.swap"
    assert chain["first_batch"]["name"] == "serve.batch"
    summary = summarize(stitched)
    assert summary["complete"] and summary["trace"] == "fedtr-v4"
    assert summary["stages"] == [
        "client.push", "client.train", "fed.flush", "serve.batch", "serve.swap",
    ]
    # A missing swap breaks completeness but never the stitch itself.
    broken = stitch(load_records(_synthetic_chain_files(tmp_path, break_stage="serve.swap")))
    assert not broken["complete"]
    assert broken["best"]["first_batch"] is not None


def test_stitch_cli_exit_codes(tmp_path, capsys):
    from fedcrack_tpu.tools import trace_stitch

    paths = _synthetic_chain_files(tmp_path)
    out_json = str(tmp_path / "stitched.json")
    rc = trace_stitch.main(
        paths + ["--require", "client.push,fed.flush,serve.swap,serve.batch",
                 "--json", out_json]
    )
    assert rc == 0
    assert json.load(open(out_json))["n_complete"] == 1
    summary = json.loads(capsys.readouterr().out)
    assert summary["complete"]
    broken = _synthetic_chain_files(tmp_path, break_stage="serve.batch")
    assert trace_stitch.main(broken) == 1  # default: demand a complete chain
