"""ResUNet shape/structure parity with SURVEY.md §2.3."""

import jax
import jax.numpy as jnp
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models import ResUNet, get_model
from fedcrack_tpu.models.resunet import init_variables, predict, upsample2x


@pytest.fixture(scope="module")
def variables():
    return init_variables(jax.random.key(0))


def test_output_shape_matches_mask(variables):
    """128x128x3 in -> 128x128x1 logits out (full-resolution masks)."""
    model = ResUNet()
    x = jnp.zeros((2, 128, 128, 3))
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 128, 128, 1)


def test_bottleneck_spatial_bookkeeping():
    """Stem /2 and three pools /2: 128 -> 8 at the bottleneck (SURVEY §2.3)."""
    assert 128 // 2 // 2 // 2 // 2 == 8


def test_train_mode_updates_batch_stats(variables):
    model = ResUNet()
    x = jax.random.normal(jax.random.key(1), (2, 128, 128, 3))
    logits, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (2, 128, 128, 1)
    # running stats must actually move
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(
        not jnp.allclose(o, n) for o, n in zip(old, new)
    ), "batch_stats unchanged in train mode"


def test_param_structure_matches_reference_layer_inventory(variables):
    """One stem, 3 encoder blocks, 4 decoder blocks, 1 head (client_fit_model.py:92-150)."""
    params = variables["params"]
    names = set(params.keys())
    assert "stem_conv" in names and "stem_bn" in names and "head" in names
    for i in range(3):
        for suffix in ("sep1", "bn1", "sep2", "bn2", "res"):
            assert f"enc{i}_{suffix}" in names, f"missing enc{i}_{suffix}"
    for i in range(4):
        for suffix in ("convT1", "bn1", "convT2", "bn2", "res"):
            assert f"dec{i}_{suffix}" in names, f"missing dec{i}_{suffix}"
    # encoder separable convs: depthwise has no bias, pointwise does (Keras parity)
    sep = params["enc0_sep1"]
    assert "bias" not in sep["depthwise"]
    assert "bias" in sep["pointwise"]


def test_param_count_matches_keras_reference(variables):
    """The Keras builder reports 2,054,369 trainable params + 3,776 BN moving
    stats for this net (measured by building client_fit_model.py:92-150's
    architecture in Keras)."""
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    n_stats = sum(p.size for p in jax.tree_util.tree_leaves(variables["batch_stats"]))
    assert n_params == 2_054_369, f"got {n_params}"
    assert n_stats == 3_776, f"got {n_stats}"


def test_predict_in_unit_interval(variables):
    x = jax.random.normal(jax.random.key(2), (1, 128, 128, 3))
    probs = predict(variables, x)
    assert probs.shape == (1, 128, 128, 1)
    assert float(probs.min()) >= 0.0 and float(probs.max()) <= 1.0


def test_upsample2x_nearest():
    x = jnp.arange(4.0).reshape(1, 2, 2, 1)
    y = upsample2x(x)
    assert y.shape == (1, 4, 4, 1)
    assert float(y[0, 0, 0, 0]) == 0.0 and float(y[0, 1, 1, 0]) == 0.0
    assert float(y[0, 3, 3, 0]) == 3.0


def test_registry_accepts_legacy_alias():
    """The reference advertises 'mobilenet_v2' (fl_server.py:75) but means the U-Net."""
    m = get_model("mobilenet_v2")
    assert isinstance(m, ResUNet)
    with pytest.raises(KeyError):
        get_model("resnet50")


def test_bf16_compute_f32_params():
    cfg = ModelConfig(compute_dtype="bfloat16")
    v = init_variables(jax.random.key(0), cfg)
    leaves = jax.tree_util.tree_leaves(v["params"])
    assert all(p.dtype == jnp.float32 for p in leaves)
    model = ResUNet(config=cfg)
    logits = model.apply(v, jnp.zeros((1, 128, 128, 3)), train=False)
    assert logits.dtype == jnp.float32  # head promotes to f32 for the loss


def test_jit_compiles_once_static_shapes(variables):
    model = ResUNet()
    fn = jax.jit(lambda v, x: model.apply(v, x, train=False))
    x = jnp.zeros((1, 128, 128, 3))
    fn(variables, x).block_until_ready()
    assert fn._cache_size() == 1
    fn(variables, x + 1).block_until_ready()
    assert fn._cache_size() == 1


def test_head_commutes_with_final_upsample(variables):
    """The round-5 fusion invariant, pinned on the MODEL's actual op order:
    the head must execute at HALF resolution (the deferral is real, not
    just documented), the model output must be exactly the nearest-neighbor
    upsample of that half-resolution head output, and the literal
    Keras/reference order (head AFTER the upsample) must reproduce the same
    logits bit-for-bit — replicated pixels produce replicated dot
    products."""
    config = ModelConfig(img_size=32)
    model = ResUNet(config=config)
    rng = jax.random.PRNGKey(3)
    images = jax.random.uniform(rng, (2, 32, 32, 3), jnp.float32)
    logits, state = model.apply(
        variables,
        images,
        train=False,
        capture_intermediates=True,
        mutable=["intermediates"],
    )
    head_out = state["intermediates"]["head"]["__call__"][0]

    # The deferral is in effect: head ran at half resolution, and the final
    # model op is exactly one nearest-neighbor upsample of its output.
    assert head_out.shape == (2, 16, 16, 1)
    assert logits.shape == (2, 32, 32, 1)
    assert jnp.array_equal(logits, upsample2x(head_out))

    # Keras/reference order on the same weights: a hand-built 1x1 head
    # applied AFTER upsampling commutes bit-exactly, so the deferred model
    # and the literal op order agree for any feature map.
    head_k = variables["params"]["head"]["kernel"].astype(jnp.float32)
    head_b = variables["params"]["head"]["bias"].astype(jnp.float32)
    f = jax.random.normal(jax.random.PRNGKey(4), (2, 16, 16, head_k.shape[2]))

    def head(x):
        return jnp.tensordot(x, head_k[0, 0], axes=[[3], [0]]) + head_b

    assert jnp.array_equal(head(upsample2x(f)), upsample2x(head(f)))
