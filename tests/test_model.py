"""ResUNet shape/structure parity with SURVEY.md §2.3, plus the layout-
transform invariants (round 6): the space-to-depth stem and channel-packed
residual projections are exact re-expressions of the reference math over the
SAME parameter tree — reverting or degrading a transform fails here, not
just in a benchmark."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models import ResUNet, get_model
from fedcrack_tpu.models.resunet import (
    depth_to_space,
    fold_stem_kernel_s2d,
    fold_stem_kernel_s2d_full,
    init_variables,
    pack_res_kernel,
    predict,
    space_to_depth,
    unfold_stem_kernel_s2d,
    unfold_stem_kernel_s2d_full,
    unpack_res_kernel,
    upsample2x,
)


@pytest.fixture(scope="module")
def variables():
    return init_variables(jax.random.key(0))


def test_output_shape_matches_mask(variables):
    """128x128x3 in -> 128x128x1 logits out (full-resolution masks)."""
    model = ResUNet()
    x = jnp.zeros((2, 128, 128, 3))
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 128, 128, 1)


def test_bottleneck_spatial_bookkeeping():
    """Stem /2 and three pools /2: 128 -> 8 at the bottleneck (SURVEY §2.3)."""
    assert 128 // 2 // 2 // 2 // 2 == 8


def test_train_mode_updates_batch_stats(variables):
    model = ResUNet()
    x = jax.random.normal(jax.random.key(1), (2, 128, 128, 3))
    logits, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (2, 128, 128, 1)
    # running stats must actually move
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(
        not jnp.allclose(o, n) for o, n in zip(old, new)
    ), "batch_stats unchanged in train mode"


def test_param_structure_matches_reference_layer_inventory(variables):
    """One stem, 3 encoder blocks, 4 decoder blocks, 1 head (client_fit_model.py:92-150)."""
    params = variables["params"]
    names = set(params.keys())
    assert "stem_conv" in names and "stem_bn" in names and "head" in names
    for i in range(3):
        for suffix in ("sep1", "bn1", "sep2", "bn2", "res"):
            assert f"enc{i}_{suffix}" in names, f"missing enc{i}_{suffix}"
    for i in range(4):
        for suffix in ("convT1", "bn1", "convT2", "bn2", "res"):
            assert f"dec{i}_{suffix}" in names, f"missing dec{i}_{suffix}"
    # encoder separable convs: depthwise has no bias, pointwise does (Keras parity)
    sep = params["enc0_sep1"]
    assert "bias" not in sep["depthwise"]
    assert "bias" in sep["pointwise"]


def test_param_count_matches_keras_reference(variables):
    """The Keras builder reports 2,054,369 trainable params + 3,776 BN moving
    stats for this net (measured by building client_fit_model.py:92-150's
    architecture in Keras)."""
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    n_stats = sum(p.size for p in jax.tree_util.tree_leaves(variables["batch_stats"]))
    assert n_params == 2_054_369, f"got {n_params}"
    assert n_stats == 3_776, f"got {n_stats}"


def test_predict_in_unit_interval(variables):
    x = jax.random.normal(jax.random.key(2), (1, 128, 128, 3))
    probs = predict(variables, x)
    assert probs.shape == (1, 128, 128, 1)
    assert float(probs.min()) >= 0.0 and float(probs.max()) <= 1.0


def test_upsample2x_nearest():
    x = jnp.arange(4.0).reshape(1, 2, 2, 1)
    y = upsample2x(x)
    assert y.shape == (1, 4, 4, 1)
    assert float(y[0, 0, 0, 0]) == 0.0 and float(y[0, 1, 1, 0]) == 0.0
    assert float(y[0, 3, 3, 0]) == 3.0


def test_registry_accepts_legacy_alias():
    """The reference advertises 'mobilenet_v2' (fl_server.py:75) but means the U-Net."""
    m = get_model("mobilenet_v2")
    assert isinstance(m, ResUNet)
    with pytest.raises(KeyError):
        get_model("resnet50")


def test_bf16_compute_f32_params():
    cfg = ModelConfig(compute_dtype="bfloat16")
    v = init_variables(jax.random.key(0), cfg)
    leaves = jax.tree_util.tree_leaves(v["params"])
    assert all(p.dtype == jnp.float32 for p in leaves)
    model = ResUNet(config=cfg)
    logits = model.apply(v, jnp.zeros((1, 128, 128, 3)), train=False)
    assert logits.dtype == jnp.float32  # head promotes to f32 for the loss


def test_jit_compiles_once_static_shapes(variables):
    model = ResUNet()
    fn = jax.jit(lambda v, x: model.apply(v, x, train=False))
    x = jnp.zeros((1, 128, 128, 3))
    fn(variables, x).block_until_ready()
    assert fn._cache_size() == 1
    fn(variables, x + 1).block_until_ready()
    assert fn._cache_size() == 1


# ---- layout transforms (round 6) -------------------------------------------


def _layout_cfg(img_size=128, **kw):
    return ModelConfig(img_size=img_size, **kw)


def test_space_to_depth_channel_order_and_inverse():
    """Packed channel = (di*2+dj)*C + c — the documented block-position-major
    order every fold/packing helper and the host-side stager rely on."""
    x = jnp.arange(2 * 2 * 3, dtype=jnp.float32).reshape(1, 2, 2, 3)
    p = space_to_depth(x)
    assert p.shape == (1, 1, 1, 12)
    for di in range(2):
        for dj in range(2):
            for c in range(3):
                assert float(p[0, 0, 0, (di * 2 + dj) * 3 + c]) == float(
                    x[0, di, dj, c]
                )
    assert jnp.array_equal(depth_to_space(p), x)


def test_host_and_device_space_to_depth_agree():
    """data.pipeline.space_to_depth_images (staging twin) must pack
    identically to the model's device-side transform — on batch arrays AND
    the [C, steps, B, ...] round layout, uint8 and float32."""
    from fedcrack_tpu.data.pipeline import space_to_depth_images

    rng = np.random.default_rng(0)
    batch = rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8)
    assert np.array_equal(
        space_to_depth_images(batch), np.asarray(space_to_depth(jnp.asarray(batch)))
    )
    stacked = rng.random((2, 3, 2, 32, 32, 3), dtype=np.float32)
    packed = space_to_depth_images(stacked)
    assert packed.shape == (2, 3, 2, 16, 16, 12)
    assert np.array_equal(
        packed[1, 2], np.asarray(space_to_depth(jnp.asarray(stacked[1, 2])))
    )


def test_fold_unfold_round_trips_are_exact(variables):
    """The weight-export inverses recover the reference kernels bitwise."""
    k = variables["params"]["stem_conv"]["kernel"]
    assert jnp.array_equal(unfold_stem_kernel_s2d(fold_stem_kernel_s2d(k)), k)
    assert jnp.array_equal(
        unfold_stem_kernel_s2d_full(fold_stem_kernel_s2d_full(k)), k
    )
    r = variables["params"]["enc0_res"]["kernel"]
    assert jnp.array_equal(unpack_res_kernel(pack_res_kernel(r)), r)


def test_layout_flags_do_not_change_params(variables):
    """Initialization is IDENTICAL across layouts (same param tree, same RNG
    folds) — the property that keeps h5 import/export, FedAvg, the wire
    format and checkpoints layout-blind."""
    for stem, res in (("s2d", "reference"), ("s2d_full", "packed"), ("s2d", "packed")):
        cfg = _layout_cfg(stem_layout=stem, res_layout=res)
        v = init_variables(jax.random.key(0), cfg)
        ref_leaves = jax.tree_util.tree_leaves(variables)
        for a, b in zip(ref_leaves, jax.tree_util.tree_leaves(v)):
            assert a.shape == b.shape
            assert jnp.array_equal(a, b)


# 128 px (the flagship size class) stays tier-1; the 256 px
# belt-and-suspenders variant is slow-marked (round-14 budget re-balance —
# a second full-size forward-parity compile, same code path).
@pytest.mark.parametrize(
    "img", [128, pytest.param(256, marks=pytest.mark.slow)]
)
def test_s2d_layout_bit_exact_random_and_fixture_inputs(variables, img):
    """THE transform pin (ISSUE r6): stem_layout='s2d' + res_layout='packed'
    reproduce the reference layout's logits BIT-EXACTLY at 128 and 256 px,
    on random inputs and on the synthetic crack fixtures — same weights,
    different executed program. (Weights are resolution-independent, so the
    module fixture serves both sizes.)"""
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    ref_model = ResUNet(config=_layout_cfg(img))
    s2d_model = ResUNet(
        config=_layout_cfg(img, stem_layout="s2d", res_layout="packed")
    )

    rand = jax.random.uniform(jax.random.key(7), (2, img, img, 3), jnp.float32)
    fixture, _ = synth_crack_batch(2, img_size=img, seed=3)
    for x in (rand, jnp.asarray(fixture)):
        ref = ref_model.apply(variables, x, train=False)
        out = s2d_model.apply(variables, x, train=False)
        assert jnp.array_equal(ref, out), "s2d layout diverged from reference"


def test_s2d_layout_accepts_packed_input(variables):
    """The staged-packed input path ([N,H/2,W/2,12], space_to_depth) is the
    same program family and stays bit-exact for both s2d variants."""
    x = jax.random.uniform(jax.random.key(9), (2, 128, 128, 3), jnp.float32)
    xp = space_to_depth(x)
    ref = ResUNet(config=_layout_cfg()).apply(variables, x, train=False)
    for stem in ("s2d", "s2d_full"):
        model = ResUNet(config=_layout_cfg(stem_layout=stem))
        unpacked = model.apply(variables, x, train=False)
        packed = model.apply(variables, xp, train=False)
        assert jnp.array_equal(unpacked, packed)
        if stem == "s2d":
            assert jnp.array_equal(ref, packed)


def test_s2d_train_mode_forward_bit_exact(variables):
    """Train-mode forward (BN batch moments) is bit-exact too — the property
    that made the mesh-round Adam step reproduce reference-layout weights
    bitwise in the cross-plane check."""
    x = jax.random.uniform(jax.random.key(11), (2, 128, 128, 3), jnp.float32)
    ref_logits, ref_state = ResUNet(config=_layout_cfg()).apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    s2d_logits, s2d_state = ResUNet(
        config=_layout_cfg(stem_layout="s2d", res_layout="packed")
    ).apply(variables, x, train=True, mutable=["batch_stats"])
    assert jnp.array_equal(ref_logits, s2d_logits)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state), jax.tree_util.tree_leaves(s2d_state)
    ):
        assert jnp.array_equal(a, b)


def test_s2d_full_is_exact_arithmetic_but_reassociated(variables):
    """stem_layout='s2d_full' computes the same math (same multiplies plus
    exact zero taps) but XLA reassociates the longer contraction: agreement
    is ulp-level, NOT bitwise — the documented reason the fully folded
    stride-1 stem is an A/B probe while 's2d' is the bit-exact default
    transform (models/resunet.py module docstring)."""
    x = jax.random.uniform(jax.random.key(13), (2, 128, 128, 3), jnp.float32)
    ref = ResUNet(config=_layout_cfg()).apply(variables, x, train=False)
    out = ResUNet(config=_layout_cfg(stem_layout="s2d_full")).apply(
        variables, x, train=False
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-4, rtol=1e-4)


def test_invalid_layout_flags_rejected():
    with pytest.raises(ValueError, match="stem_layout"):
        ModelConfig(stem_layout="nope")
    with pytest.raises(ValueError, match="res_layout"):
        ModelConfig(res_layout="nope")


def test_s2d_rejects_wrong_channel_count():
    cfg = _layout_cfg(stem_layout="s2d")
    v = init_variables(jax.random.key(0), cfg)
    model = ResUNet(config=cfg)
    with pytest.raises(ValueError, match="channels"):
        model.apply(v, jnp.zeros((1, 64, 64, 5)), train=False)


def test_head_commutes_with_final_upsample(variables):
    """The round-5 fusion invariant, pinned on the MODEL's actual op order:
    the head must execute at HALF resolution (the deferral is real, not
    just documented), the model output must be exactly the nearest-neighbor
    upsample of that half-resolution head output, and the literal
    Keras/reference order (head AFTER the upsample) must reproduce the same
    logits bit-for-bit — replicated pixels produce replicated dot
    products."""
    config = ModelConfig(img_size=32)
    model = ResUNet(config=config)
    rng = jax.random.PRNGKey(3)
    images = jax.random.uniform(rng, (2, 32, 32, 3), jnp.float32)
    logits, state = model.apply(
        variables,
        images,
        train=False,
        capture_intermediates=True,
        mutable=["intermediates"],
    )
    head_out = state["intermediates"]["head"]["__call__"][0]

    # The deferral is in effect: head ran at half resolution, and the final
    # model op is exactly one nearest-neighbor upsample of its output.
    assert head_out.shape == (2, 16, 16, 1)
    assert logits.shape == (2, 32, 32, 1)
    assert jnp.array_equal(logits, upsample2x(head_out))

    # Keras/reference order on the same weights: a hand-built 1x1 head
    # applied AFTER upsampling commutes bit-exactly, so the deferred model
    # and the literal op order agree for any feature map.
    head_k = variables["params"]["head"]["kernel"].astype(jnp.float32)
    head_b = variables["params"]["head"]["bias"].astype(jnp.float32)
    f = jax.random.normal(jax.random.PRNGKey(4), (2, 16, 16, head_k.shape[2]))

    def head(x):
        return jnp.tensordot(x, head_k[0, 0], axes=[[3], [0]]) + head_b

    assert jnp.array_equal(head(upsample2x(f)), upsample2x(head(f)))
