"""Tests for the double-buffered multi-round mesh federation driver.

The load-bearing property (round-3 verdict "what's weak" #2): staging round
r+1 while round r computes must be a pure latency optimization — the final
global weights are bit-identical to sequential staging, because staging is
data-independent of the in-flight round.
"""

import jax
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.parallel import (
    build_federated_round,
    make_mesh,
    run_mesh_federation,
    shuffled_epoch_data,
    stack_client_data,
)

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
STEPS, BATCH, N_CLIENTS, ROUNDS = 2, 4, 2, 3


@pytest.fixture(scope="module")
def round_fn_and_mesh():
    mesh = make_mesh(N_CLIENTS, 1)
    round_fn = build_federated_round(mesh, TINY, learning_rate=1e-3, local_epochs=1)
    return round_fn, mesh


def _fresh_data_fn(seed0=0):
    """Deterministic per-round data: a new shard every round (forces
    restaging), same values for every caller."""

    def data_fn(r):
        per_client = [
            synth_crack_batch(
                STEPS * BATCH, img_size=TINY.img_size, seed=seed0 + 10 * r + i
            )
            for i in range(N_CLIENTS)
        ]
        images, masks = stack_client_data(per_client, STEPS, BATCH)
        active = np.ones(N_CLIENTS, np.float32)
        n_samples = np.full(N_CLIENTS, float(STEPS * BATCH), np.float32)
        return images, masks, active, n_samples

    return data_fn


def _init_vars():
    from fedcrack_tpu.train.local import create_train_state

    return create_train_state(jax.random.key(0), TINY).variables


def _assert_trees_equal(got, want):
    gl = jax.tree_util.tree_leaves(got)
    wl = jax.tree_util.tree_leaves(want)
    assert len(gl) == len(wl)
    for g, w in zip(gl, wl):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_overlap_matches_sequential(round_fn_and_mesh):
    round_fn, mesh = round_fn_and_mesh
    v_overlap, rec_overlap = run_mesh_federation(
        round_fn, _init_vars(), _fresh_data_fn(), ROUNDS, mesh, overlap_staging=True
    )
    v_seq, rec_seq = run_mesh_federation(
        round_fn, _init_vars(), _fresh_data_fn(), ROUNDS, mesh, overlap_staging=False
    )
    _assert_trees_equal(v_overlap, v_seq)
    for ro, rs in zip(rec_overlap, rec_seq):
        for k in ro.metrics:
            np.testing.assert_array_equal(ro.metrics[k], rs.metrics[k])
    # All but the last round staged the next round's data concurrently.
    assert [r.overlapped for r in rec_overlap] == [True, True, False]
    assert all(not r.overlapped for r in rec_seq)
    # staging_s is the host-blocking staging paid for THIS round's data
    # (round-7 boundary-term fix): the initial transfer lands on the first
    # record in BOTH modes; after that, overlap mode hides staging (0.0)
    # while sequential mode pays it for every round — so sequential session
    # totals now account for exactly one staging period per round, none
    # dropped at either boundary.
    assert rec_overlap[0].staging_s > 0.0
    assert all(r.staging_s == 0.0 for r in rec_overlap[1:])
    assert all(r.staging_s > 0.0 for r in rec_seq)


def test_none_data_reuses_buffers(round_fn_and_mesh):
    """data_fn returning None after round 0 must train on the same staged
    shard every round — equal to a data_fn that re-returns the same arrays."""
    round_fn, mesh = round_fn_and_mesh
    fixed = _fresh_data_fn()(0)

    v_reuse, rec_reuse = run_mesh_federation(
        round_fn, _init_vars(), lambda r: fixed if r == 0 else None, ROUNDS, mesh
    )
    v_reship, _ = run_mesh_federation(
        round_fn, _init_vars(), lambda r: fixed, ROUNDS, mesh
    )
    _assert_trees_equal(v_reuse, v_reship)
    # Only the first round shipped bytes; no round after it overlapped
    # (there was nothing to stage).
    assert rec_reuse[0].staged_bytes > 0
    assert all(r.staged_bytes == 0 for r in rec_reuse[1:])
    assert all(not r.overlapped for r in rec_reuse)


def test_on_round_hook_sees_every_round(round_fn_and_mesh):
    round_fn, mesh = round_fn_and_mesh
    seen = []

    def hook(record, variables):
        # The hook's variables are the round's output, still usable on
        # device: a metrics sink / checkpointer can device_get them.
        loss = float(np.asarray(record.metrics["loss"])[0])
        seen.append((record.round_idx, loss, variables))

    final_vars, records = run_mesh_federation(
        round_fn, _init_vars(), _fresh_data_fn(), ROUNDS, mesh, on_round=hook
    )
    assert [s[0] for s in seen] == list(range(ROUNDS))
    assert len(records) == ROUNDS
    assert all(np.isfinite(s[1]) for s in seen)
    # The hook sees each round's OUTPUT: the last hook call's variables are
    # exactly what the driver returns as the final global model.
    _assert_trees_equal(seen[-1][2], final_vars)
    # And the rounds actually chain: consecutive hook variables differ.
    l0 = jax.tree_util.tree_leaves(seen[0][2])
    l1 = jax.tree_util.tree_leaves(seen[1][2])
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(l0, l1)
    )


def test_cohort_change_between_rounds(round_fn_and_mesh):
    """data_fn can shrink the cohort mid-federation (a client drops out):
    the masked psum divisor follows the new active mask, no recompilation."""
    round_fn, mesh = round_fn_and_mesh
    base = _fresh_data_fn()

    def data_fn(r):
        images, masks, active, n_samples = base(r)
        if r >= 1:
            active = active.copy()
            active[1] = 0.0  # client 1 silent from round 1 on
        return images, masks, active, n_samples

    v, records = run_mesh_federation(round_fn, _init_vars(), data_fn, 2, mesh)
    assert len(records) == 2
    assert list(records[1].metrics["active"]) == [1.0, 0.0]
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree_util.tree_leaves(v))


def test_first_round_data_required(round_fn_and_mesh):
    round_fn, mesh = round_fn_and_mesh
    with pytest.raises(ValueError, match="first round has no data"):
        run_mesh_federation(round_fn, _init_vars(), lambda r: None, 1, mesh)
    with pytest.raises(ValueError, match="n_rounds"):
        run_mesh_federation(round_fn, _init_vars(), _fresh_data_fn(), 0, mesh)


# Tier-1 budget re-balance (round 14, r4/r9/r12/r13 precedent): the
# spatial round PROGRAM's numerics stay tier-1 in test_spatial +
# test_parallel; this is the driver-integration twin (~16 s of spatial
# compiles) and the driver loop itself is tier-1-pinned by six other
# tests in this module.
@pytest.mark.slow
def test_driver_drives_spatial_federated_round():
    """The driver's ``image_spec`` parameter composes with the
    spatially-sharded round builder: a Mesh(('clients','space')) federation
    where each client's fit is halo-exchange sharded over image height,
    driven for 2 rounds with per-round restaging."""
    import jax
    from jax.sharding import PartitionSpec as P

    from fedcrack_tpu.parallel import build_spatial_federated_round, make_mesh
    from fedcrack_tpu.train.local import create_train_state

    n_clients, n_space, steps, batch = 2, 2, 2, 2
    # H=32 satisfies the 16 x n_space divisibility contract.
    cfg = ModelConfig(
        img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    mesh = make_mesh(n_clients, n_space, axis_names=("clients", "space"))
    round_fn = build_spatial_federated_round(
        mesh, cfg, learning_rate=1e-3, local_epochs=1
    )
    spec = P("clients", None, None, "space")

    def data_fn(r):
        per_client = [
            synth_crack_batch(steps * batch, img_size=32, seed=40 + 10 * r + i)
            for i in range(n_clients)
        ]
        images, masks = stack_client_data(per_client, steps, batch)
        active = np.ones(n_clients, np.float32)
        n_samples = np.full(n_clients, float(steps * batch), np.float32)
        return images, masks, active, n_samples

    tmpl = create_train_state(jax.random.key(0), cfg)
    variables, records = run_mesh_federation(
        round_fn, tmpl.variables, data_fn, 2, mesh, image_spec=spec
    )
    assert len(records) == 2
    assert records[0].overlapped and not records[1].overlapped
    for rec in records:
        assert np.isfinite(rec.metrics["loss"]).all()
    assert all(
        np.isfinite(np.asarray(l)).all() for l in jax.tree_util.tree_leaves(variables)
    )


@pytest.mark.slow
def test_mesh_program_reaches_absolute_iou_floor():
    """Quality THROUGH the mesh program (round-3 verdict item 4): every
    earlier quality number flowed through the host plane, with the mesh rows
    borrowing IoU via the bit-equality cross-check. Here the flagship
    artifact itself — ``build_federated_round``'s output, driven by
    ``run_mesh_federation`` — must land at held-out IoU >= 0.35 after
    3 rounds, the same calibrated floor as the host-plane twin
    (test_train.py::test_federated_reaches_absolute_iou_floor; calibration:
    bench_runs/r03_quality_gate_calibration.json). 2 clients x 1 device on
    the virtual mesh (the other 6 devices stay idle — collectives spin-wait
    on this 1-core host, and a 2-device program halves that contention)."""
    import jax

    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.train.local import (
        create_train_state,
        evaluate,
        recalibrate_batch_stats,
    )

    model_cfg = ModelConfig(img_size=64)
    steps, batch, n_clients, rounds = 6, 8, 2, 3
    mesh = make_mesh(n_clients, 1)
    round_fn = build_federated_round(
        mesh, model_cfg, learning_rate=1e-3, local_epochs=3, pos_weight=5.0
    )
    pools = [
        synth_crack_batch(steps * batch, 64, seed=10 + i, min_thickness=3)
        for i in range(n_clients)
    ]
    rngs = [np.random.default_rng(100 + i) for i in range(n_clients)]
    active = np.ones(n_clients, np.float32)
    n_samples = np.full(n_clients, float(steps * batch), np.float32)

    def data_fn(r):
        # Fresh per-round shuffle of each client's fixed pool (the host twin
        # reshuffles per epoch via ArrayDataset; per round is the mesh
        # plane's granularity — batches inside a round are a scan).
        parts = [
            shuffled_epoch_data(p[0], p[1], steps, batch, rng)
            for p, rng in zip(pools, rngs)
        ]
        images = np.concatenate([x[0] for x in parts])
        masks = np.concatenate([x[1] for x in parts])
        return images, masks, active, n_samples

    tmpl = create_train_state(jax.random.key(0), model_cfg)
    variables, records = run_mesh_federation(
        round_fn, tmpl.variables, data_fn, rounds, mesh
    )

    # Train-mode IoU (final local epoch, cohort mean) must improve across
    # rounds — the federation is learning, not just averaging.
    mean_iou = [float(np.mean(r.metrics["iou"])) for r in records]
    assert mean_iou[-1] > mean_iou[0], f"no IoU improvement: {mean_iou}"

    # Held-out absolute floor on the aggregated global model, BN-recalibrated
    # (the server's eval path), at the training pos_weight.
    ev_i, ev_m = synth_crack_batch(32, 64, seed=999, min_thickness=3)
    eval_ds = ArrayDataset(ev_i, ev_m, batch_size=8, shuffle=False, drop_last=False)
    st = tmpl.replace_variables(jax.device_get(variables))
    st = recalibrate_batch_stats(st, eval_ds, model_cfg)
    m = evaluate(st, eval_ds, pos_weight=5.0)
    assert m["iou"] >= 0.35, (
        f"mesh-program federated held-out IoU {m['iou']:.3f} under the 0.35 floor "
        f"(train IoU trajectory {mean_iou})"
    )


def test_shuffled_epoch_data_layout():
    rng = np.random.default_rng(0)
    pool_i, pool_m = synth_crack_batch(10, img_size=16, seed=0)
    images, masks = shuffled_epoch_data(pool_i, pool_m, steps=2, batch_size=4, rng=rng)
    assert images.shape == (1, 2, 4, 16, 16, 3)
    assert masks.shape == (1, 2, 4, 16, 16, 1)
    # Samples are drawn without replacement from the pool.
    flat = images.reshape(8, -1)
    pool_flat = pool_i.reshape(10, -1)
    matches = (flat[:, None, :] == pool_flat[None, :, :]).all(-1)
    assert (matches.sum(axis=1) == 1).all()
    assert matches.any(axis=0).sum() == 8  # 8 distinct pool rows used
    with pytest.raises(ValueError, match="pool has"):
        shuffled_epoch_data(pool_i, pool_m, steps=4, batch_size=4, rng=rng)
