"""The scatter-free max-pool VJP: forward bit-parity with nn.max_pool,
gradient parity with XLA's SelectAndScatter lowering (including ties)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.ops.pooling import max_pool_3x3_s2


def _ref_pool(x):
    return nn.max_pool(x, window_shape=(3, 3), strides=(2, 2), padding="SAME")


@pytest.mark.parametrize("shape", [(2, 16, 16, 4), (1, 15, 17, 3), (3, 7, 7, 1)])
def test_forward_bit_identical(shape):
    x = jax.random.normal(jax.random.key(0), shape, jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(max_pool_3x3_s2(x)), np.asarray(_ref_pool(x))
    )


@pytest.mark.parametrize("shape", [(2, 16, 16, 4), (1, 15, 17, 3), (3, 7, 7, 1)])
def test_gradient_matches_select_and_scatter(shape):
    """Both lowerings must route each output's cotangent to the same argmax.
    Integer-valued cotangents make the per-input sums exact in float32, so
    equality proves identical ROUTING — a float cotangent would add
    reassociation noise where one input feeds several windows."""
    x = jax.random.normal(jax.random.key(1), shape, jnp.float32)
    g = jnp.asarray(
        np.random.default_rng(2).integers(-8, 9, _ref_pool(x).shape), jnp.float32
    )

    def loss(pool):
        return lambda v: jnp.sum(pool(v) * g)

    got = jax.grad(loss(max_pool_3x3_s2))(x)
    want = jax.grad(loss(_ref_pool))(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # float cotangents: identical up to summation order (1-2 ulp)
    gf = jax.random.normal(jax.random.key(2), _ref_pool(x).shape, jnp.float32)
    got_f = jax.grad(lambda v: jnp.sum(max_pool_3x3_s2(v) * gf))(x)
    want_f = jax.grad(lambda v: jnp.sum(_ref_pool(v) * gf))(x)
    np.testing.assert_allclose(np.asarray(got_f), np.asarray(want_f), rtol=1e-6, atol=1e-6)


def test_gradient_ties_match_xla_tiebreak():
    """Tied window maxima: SelectAndScatter routes to the first match in
    row-major window order — the custom backward's claim order matches, so
    even degenerate (constant) inputs agree exactly."""
    for x in [
        jnp.zeros((1, 8, 8, 2), jnp.float32),
        jnp.ones((2, 9, 6, 3), jnp.float32),
        jnp.asarray(
            np.random.default_rng(7).integers(0, 3, (2, 12, 12, 2)), jnp.float32
        ),  # heavy ties from a 3-value alphabet
    ]:
        g = jnp.arange(np.prod(_ref_pool(x).shape), dtype=jnp.float32).reshape(
            _ref_pool(x).shape
        )
        got = jax.grad(lambda v: jnp.sum(max_pool_3x3_s2(v) * g))(x)
        want = jax.grad(lambda v: jnp.sum(_ref_pool(v) * g))(x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_nan_window_still_routes_gradient():
    """A NaN activation must not silently zero the pool gradient: the claim
    mask uses ~(cand < out) with SAME-pad candidates barred, so a NaN
    window max still claims one REAL offset and the cotangent flows
    (divergence stays visible upstream)."""
    # interior NaN, even size (no pad ambiguity)
    x = jax.random.normal(jax.random.key(6), (1, 8, 8, 1), jnp.float32)
    x = x.at[0, 2, 2, 0].set(jnp.nan)
    g = jax.grad(lambda v: jnp.sum(max_pool_3x3_s2(v)))(x)
    assert float(jnp.abs(g[0, 2, 2, 0])) > 0.0

    # corner NaN on an ODD size: pad_lo = 1, so the corner window's first
    # row-major candidate is a pad cell — without the validity mask the
    # pad claims the cotangent and the slice discards it (gradient mass
    # silently lost; reproduced before the fix: total 24.0 vs 25.0).
    x = jnp.zeros((1, 9, 9, 1), jnp.float32).at[0, 0, 0, 0].set(jnp.nan)
    g = jax.grad(lambda v: jnp.sum(max_pool_3x3_s2(v)))(x)
    assert float(jnp.abs(g[0, 0, 0, 0])) > 0.0
    out_size = max_pool_3x3_s2(jnp.zeros((1, 9, 9, 1))).size
    assert float(jnp.sum(g)) == pytest.approx(float(out_size))


def test_gradient_mass_conserved():
    """Every output routes its cotangent to exactly one input."""
    x = jax.random.normal(jax.random.key(3), (2, 16, 16, 4), jnp.float32)
    ones = jnp.ones(_ref_pool(x).shape, jnp.float32)
    got = jax.grad(lambda v: jnp.sum(max_pool_3x3_s2(v) * ones))(x)
    assert float(jnp.sum(got)) == pytest.approx(float(ones.size))


def test_bfloat16_and_jit_scan():
    """The training loop runs the op in bf16 under jit+scan."""
    x = jax.random.normal(jax.random.key(4), (2, 16, 16, 4)).astype(jnp.bfloat16)
    out = jax.jit(max_pool_3x3_s2)(x)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(_ref_pool(x), np.float32)
    )

    def step(carry, _):
        gr = jax.grad(lambda v: jnp.sum(max_pool_3x3_s2(v)))(carry)
        return carry + gr.astype(carry.dtype), None

    final, _ = jax.jit(lambda v: jax.lax.scan(step, v, None, length=3))(x)
    assert final.shape == x.shape


def test_model_forward_unchanged_by_custom_pool():
    """The U-Net's forward (pinned by h5-parity elsewhere) is bit-identical
    with the custom pool, because the forward IS the same reduce_window."""
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.models.resunet import ResUNet

    cfg = ModelConfig(
        img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    model = ResUNet(config=cfg)
    x = jax.random.normal(jax.random.key(5), (2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 32, 32, 1)
    assert bool(jnp.all(jnp.isfinite(logits)))
