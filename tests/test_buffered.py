"""Async federation (round 14): FedBuff buffered aggregation.

The non-negotiable gates, in order: (1) the buffered flush is a SORTED
fold — a pure function of the buffer contents, never of cross-client
arrival order; (2) ``buffer_k = cohort_size`` + ``staleness_alpha = 0``
degenerates to sync FedAvg BIT-exactly (the escape hatch that lets the
async plane ship without forking the trajectory contract); (3) a server
killed MID-BUFFER resumes from the statefile and flushes to the
bit-identical next global version; (4) staleness weighting follows the
closed form ``(1 + s)^-alpha`` and too-stale updates are rejected into the
history, never averaged; (5) the staleness-aware error-feedback decay
still drains ('nothing lost, only delayed' converges); (6) the mesh/cohort
drivers' round-overlap is bit-identical to the unoverlapped schedule.
"""

import dataclasses
import hashlib
import threading

import numpy as np
import pytest

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.buffered import (
    BufferedAggregator,
    async_summary,
    staleness_weight,
)
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes


def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _cfg(**kw):
    base = dict(
        max_rounds=3,
        cohort_size=3,
        registration_window_s=3600.0,
        mode="buffered",
        buffer_k=3,
        staleness_alpha=0.0,
        max_staleness=4,
    )
    base.update(kw)
    return FedConfig(**base)


def _enroll(state, names, now=0.0):
    for c in names:
        now += 1e-3
        state, rep = R.transition(state, R.Ready(cname=c, now=now))
        assert rep.status == R.SW
    return state, now


def _pull(state, c, now):
    now += 1e-3
    state, rep = R.transition(state, R.PullWeights(cname=c, now=now))
    assert rep.status == "OK"
    return state, rep, now


def _push(state, c, value, ns, now, rnd=1):
    now += 1e-3
    state, rep = R.transition(
        state,
        R.TrainDone(
            cname=c, round=rnd, blob=tree_to_bytes(_vars(value)),
            num_samples=ns, now=now,
        ),
    )
    return state, rep, now


# ---------- staleness weight closed form ----------

def test_staleness_weight_closed_form():
    assert staleness_weight(0, 0.0) == 1.0
    # alpha = 0 must be EXACTLY 1.0 for every staleness — the bit-exact
    # sync degeneration rides on ns * 1.0 == ns as the same float.
    for s in range(10):
        assert staleness_weight(s, 0.0) == 1.0
    assert staleness_weight(1, 1.0) == 0.5
    assert staleness_weight(3, 0.5) == pytest.approx(0.5)
    assert staleness_weight(2, 1.0) == pytest.approx(1.0 / 3.0)
    with pytest.raises(ValueError):
        staleness_weight(-1, 0.5)
    with pytest.raises(ValueError):
        staleness_weight(1, -0.1)


def test_config_validation():
    with pytest.raises(ValueError):
        FedConfig(mode="later")
    with pytest.raises(ValueError):
        FedConfig(buffer_k=0)
    with pytest.raises(ValueError):
        FedConfig(staleness_alpha=-1.0)
    with pytest.raises(ValueError):
        FedConfig(max_staleness=-1)
    # Buffered knobs round-trip the JSON config like everything else.
    cfg = _cfg(buffer_k=5, staleness_alpha=0.25, max_staleness=7)
    back = FedConfig.from_json(cfg.to_json())
    assert back.mode == "buffered" and back.buffer_k == 5
    assert back.staleness_alpha == 0.25 and back.max_staleness == 7


# ---------- the sorted-fold flush ----------

def test_flush_matches_sorted_fold_oracle():
    """The flushed global equals an independently computed sample-and-
    staleness-weighted FedAvg over the buffer entries in (cname, seq)
    order."""
    from fedcrack_tpu.fed.algorithms import fedavg

    cfg = _cfg(buffer_k=3, staleness_alpha=1.0, max_staleness=4)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    # a and b pull v0; a's first push flushes nothing (K=3)... push a, b,
    # then c pulls AFTER nothing changed — all staleness 0 here; instead
    # drive staleness via two-version choreography below. This test pins
    # the weighted fold itself.
    for c in ("a", "b", "c"):
        st, _, now = _pull(st, c, now)
    st, rep, now = _push(st, "a", 1.0, 10, now)
    assert rep.status == R.RESP_ACY
    st, rep, now = _push(st, "b", 3.0, 30, now)
    assert rep.status == R.RESP_ACY
    entries = sorted(st.buffer, key=lambda e: (e["cname"], e["seq"]))
    st, rep, now = _push(st, "c", 6.0, 20, now)
    assert rep.status == R.RESP_ARY
    entries = entries + [
        {"blob": tree_to_bytes(_vars(6.0)), "ns": 20, "weight": 1.0}
    ]
    oracle = fedavg(
        [tree_from_bytes(e["blob"]) for e in entries],
        [e["ns"] * e["weight"] for e in entries],
    )
    got = tree_from_bytes(st.global_blob)
    np.testing.assert_array_equal(got["params"]["w"], oracle["params"]["w"])
    assert st.history[-1]["buffer_fill"] == 3
    assert st.history[-1]["global_version"] == 1


@pytest.mark.parametrize("order", [("a", "b", "c"), ("c", "b", "a"), ("b", "c", "a")])
def test_arrival_order_independent_flush(order):
    """Permuted cross-client arrival orders flush to BYTE-identical
    globals (the sorted (cname, seq) fold)."""
    cfg = _cfg(buffer_k=3)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    for c in order:
        st, _, now = _pull(st, c, now)
    values = {"a": 1.0, "b": 3.0, "c": 6.0}
    samples = {"a": 10, "b": 30, "c": 20}
    for c in order:
        st, rep, now = _push(st, c, values[c], samples[c], now)
    ref_cfg = _cfg(buffer_k=3)
    ref = R.initial_state(ref_cfg, _vars(0.0))
    ref, rnow = _enroll(ref, ("a", "b", "c"))
    for c in ("a", "b", "c"):
        ref, _, rnow = _pull(ref, c, rnow)
    for c in ("a", "b", "c"):
        ref, _, rnow = _push(ref, c, values[c], samples[c], rnow)
    assert st.global_blob == ref.global_blob
    assert st.model_version == ref.model_version == 1


def test_alpha0_k_equals_n_degenerates_to_sync_bitexact():
    """buffer_k = cohort_size + staleness_alpha = 0 reproduces the sync
    FedAvg trajectory BIT-exactly over multiple rounds — including through
    the shared FedOpt server step (fedadam moments)."""
    values = {"a": 1.0, "b": 3.0}
    samples = {"a": 10, "b": 30}

    def drive(mode):
        kw = dict(
            max_rounds=3, cohort_size=2, registration_window_s=3600.0,
            server_optimizer="fedadam", server_lr=0.1,
        )
        if mode == "buffered":
            kw.update(mode="buffered", buffer_k=2, staleness_alpha=0.0)
        st = R.initial_state(FedConfig(**kw), _vars(0.0))
        st, now = _enroll(st, ("a", "b"))
        for rnd in range(1, 4):
            for c in ("a", "b"):
                st, _, now = _pull(st, c, now)
            for c in ("a", "b"):
                st, rep, now = _push(
                    st, c, values[c] + rnd, samples[c], now, rnd=rnd
                )
        return st

    sync = drive("sync")
    buf = drive("buffered")
    assert sync.global_blob == buf.global_blob
    assert sync.model_version == buf.model_version == 3
    assert buf.phase == R.PHASE_FINISHED


# ---------- staleness semantics ----------

def test_stale_update_weighted_by_decay():
    """A client pushing an update trained on the previous version lands
    with staleness 1 and weight (1+1)^-1 = 0.5, and the flush applies
    ns * weight — checked against the closed-form weighted mean."""
    cfg = _cfg(buffer_k=1, staleness_alpha=1.0, max_staleness=4, max_rounds=5)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    st, _, now = _pull(st, "a", now)
    st, _, now = _pull(st, "b", now)
    # a flushes v1 alone (K=1); b's pull predates it.
    st, rep, now = _push(st, "a", 2.0, 10, now)
    assert rep.status == R.RESP_ARY and st.model_version == 1
    # b trained on v0: staleness 1, accepted, weighted 0.5 — a flush
    # whose buffer is ALL stale must not replace the global (within-
    # buffer weights normalize away): the FedAsync anchor mixes
    # (1 - mix)·current + mix·buffer_mean with mix = the mean staleness
    # weight, so v2 = 0.5·v1 + 0.5·b = 0.5·2 + 0.5·4 = 3.
    st, rep, now = _push(st, "b", 4.0, 10, now)
    assert rep.status == R.RESP_ARY and st.model_version == 2
    entry = st.history[-1]
    assert entry["staleness"] == [1]
    assert entry["weights"] == [0.5]
    assert entry["mix"] == pytest.approx(0.5)
    got = tree_from_bytes(st.global_blob)["params"]["w"]
    np.testing.assert_allclose(got, 3.0, atol=1e-6)
    summary = async_summary(st.history)
    assert summary["accepted_updates"] == 2
    assert summary["global_versions"] == 2
    assert summary["staleness"]["max"] == 1.0


def test_mixed_staleness_flush_weighted_mean():
    """Two updates with different staleness in ONE flush: the buffer mean
    is the (ns * weight)-weighted mean, then the flush anchors on the
    current global by the sample-weighted MEAN staleness weight."""
    cfg = _cfg(buffer_k=2, staleness_alpha=1.0, max_staleness=4, max_rounds=5)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    for c in ("a", "b", "c"):
        st, _, now = _pull(st, c, now)
    st, rep, now = _push(st, "a", 1.0, 10, now)
    st, rep, now = _push(st, "b", 3.0, 30, now)
    assert rep.status == R.RESP_ARY and st.model_version == 1
    # v1 = (10·1 + 30·3)/40 = 2.5 (all fresh: mix == 1.0 exactly, no
    # anchor). Flush 2: c (stale, v0 base, weight 0.5, ns 20 -> eff 10) +
    # a (fresh v1 base, weight 1, ns 10 -> eff 10): buffer mean =
    # (10·6 + 10·2)/20 = 4; mix = (10 + 20·0.5)/30 = 2/3; v2 =
    # (1/3)·2.5 + (2/3)·4 = 3.5.
    st, _, now = _pull(st, "a", now)
    st, rep, now = _push(st, "c", 6.0, 20, now)
    assert rep.status == R.RESP_ACY
    st, rep, now = _push(st, "a", 2.0, 10, now)
    assert rep.status == R.RESP_ARY and st.model_version == 2
    got = tree_from_bytes(st.global_blob)["params"]["w"]
    np.testing.assert_allclose(got, 3.5, atol=1e-5)
    entry = st.history[-1]
    assert entry["mix"] == pytest.approx(2.0 / 3.0)
    assert sorted(zip(entry["clients"], entry["staleness"])) == [
        ("a", 0), ("c", 1)
    ]


def test_too_stale_rejected_and_resynced():
    """An update beyond max_staleness is recorded to the history's
    rejected map (never averaged) and the sender is handed the current
    global (NOT_WAIT — the sync straggler treatment)."""
    cfg = _cfg(buffer_k=1, staleness_alpha=0.5, max_staleness=0, max_rounds=5)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    st, _, now = _pull(st, "a", now)
    st, _, now = _pull(st, "b", now)
    st, rep, now = _push(st, "a", 2.0, 10, now)
    assert st.model_version == 1
    st, rep, now = _push(st, "b", 4.0, 10, now)
    assert rep.status == R.NOT_WAIT
    assert rep.blob == st.broadcast_blob
    assert "too stale" in st.rejected["b"]
    assert st.pulled["b"] == 1  # resynced to the current version
    # The refusal surfaces in the NEXT flush's history entry.
    st, _, now = _pull(st, "a", now)
    st, rep, now = _push(st, "a", 3.0, 10, now)
    assert "too stale" in st.history[-1]["rejected"]["b"]
    # ... and b, now current, is accepted again.
    st, _, now = _pull(st, "b", now)
    st, rep, now = _push(st, "b", 5.0, 10, now)
    assert rep.status in (R.RESP_ARY, R.FIN)


def test_push_before_pull_resyncs():
    cfg = _cfg(buffer_k=2)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    st, rep, now = _push(st, "a", 1.0, 10, now)
    assert rep.status == R.NOT_WAIT
    assert "no recorded base" in st.rejected["a"]
    assert st.pulled["a"] == 0


def test_sanitation_rejects_poison_in_buffered_mode():
    """NaN updates and corrupt frames fail loudly (REJECTED), exactly as
    in sync mode — the shared decode_and_validate_update gate."""
    cfg = _cfg(buffer_k=2)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    st, _, now = _pull(st, "a", now)
    bad = _vars(1.0)
    bad["params"]["w"] = np.full((4, 4), np.nan, np.float32)
    now += 1e-3
    st, rep = R.transition(
        st,
        R.TrainDone(
            cname="a", round=1, blob=tree_to_bytes(bad), num_samples=10, now=now
        ),
    )
    assert rep.status == R.REJECTED
    assert "a" in st.rejected and not st.buffer


def test_stale_framed_delta_decodes_against_retained_base():
    """A compressed (int8) delta pinned to a RETAINED past version
    reconstructs against that base — not the current global — and lands
    staleness-weighted."""
    from fedcrack_tpu.compress import get_codec

    cfg = _cfg(
        buffer_k=1, staleness_alpha=1.0, max_staleness=2, max_rounds=5,
        update_codec="int8",
    )
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    st, rep_a, now = _pull(st, "a", now)
    st, rep_b, now = _pull(st, "b", now)
    base0 = rep_b.blob
    # a advances the global twice; b still holds v0.
    for v in (2.0, 3.0):
        frame = get_codec("int8", client_tag="a").encode_update(
            tree_to_bytes(_vars(v)), st.broadcast_blob, round=1,
            base_version=st.model_version,
        )
        now += 1e-3
        st, rep = R.transition(
            st, R.TrainDone(cname="a", round=1, blob=frame, num_samples=10, now=now)
        )
        assert rep.status == R.RESP_ARY
        st, rep_a, now = _pull(st, "a", now)
    # b's delta against v0: staleness 2 <= max_staleness, must decode
    # against the RETAINED v0 blob bit-for-bit (the codec is seeded, so
    # the expected reconstruction is computable).
    frame_b = get_codec("int8", client_tag="b").encode_update(
        tree_to_bytes(_vars(9.0)), base0, round=1, base_version=0
    )
    pre_flush_global = tree_from_bytes(st.global_blob)["params"]["w"]
    now += 1e-3
    st, rep = R.transition(
        st, R.TrainDone(cname="b", round=1, blob=frame_b, num_samples=10, now=now)
    )
    assert rep.status == R.RESP_ARY
    entry = st.history[-1]
    assert entry["staleness"] == [2] and entry["codecs"] == ["int8"]
    # staleness 2, alpha 1: weight = mix = 1/3 — the flush blends the
    # RETAINED-base reconstruction into the current global.
    from fedcrack_tpu.compress import decode_update

    recon, _ = decode_update(
        frame_b,
        template=tree_from_bytes(base0),
        base=tree_from_bytes(base0),
        expected_base_version=0,
    )
    assert entry["mix"] == pytest.approx(1.0 / 3.0)
    keep = np.float32(1.0 - entry["mix"])  # the flush's exact expression
    take = np.float32(entry["mix"])
    want = keep * np.asarray(pre_flush_global, np.float32) + take * np.asarray(
        recon["params"]["w"], np.float32
    )
    got = tree_from_bytes(st.global_blob)
    np.testing.assert_array_equal(got["params"]["w"], want)


def test_deadline_flushes_partial_buffer():
    """round_deadline_s in buffered mode is the flush-liveness backstop: a
    PARTIAL buffer older than the deadline flushes instead of stalling the
    version counter behind absent clients."""
    cfg = _cfg(buffer_k=3, round_deadline_s=5.0, registration_window_s=1.0)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    st, _, now = _pull(st, "a", now)
    st, rep, now = _push(st, "a", 2.0, 10, now)
    assert rep.status == R.RESP_ACY and st.model_version == 0
    st, _ = R.transition(st, R.Tick(now=now + 10.0))
    assert st.model_version == 1
    assert st.history[-1]["buffer_fill"] == 1
    # An EMPTY buffer past the deadline re-arms instead of flushing.
    st, _ = R.transition(st, R.Tick(now=now + 30.0))
    assert st.model_version == 1


# ---------- statefile: mid-buffer kill -> bit-identical resume ----------

def test_statefile_midbuffer_resume_bit_identity():
    from fedcrack_tpu.ckpt.statefile import (
        server_state_from_bytes,
        server_state_to_bytes,
    )

    cfg = _cfg(buffer_k=3, staleness_alpha=1.0)
    st = R.initial_state(cfg, _vars(0.0))
    st, now = _enroll(st, ("a", "b", "c"))
    for c in ("a", "b", "c"):
        st, _, now = _pull(st, c, now)
    st, _, now = _push(st, "a", 1.0, 10, now)
    st, _, now = _push(st, "b", 3.0, 30, now)
    blob = server_state_to_bytes(st)
    restored = server_state_from_bytes(blob, cfg)
    # The snapshot is canonical: re-serializing the restored state yields
    # the identical bytes.
    assert server_state_to_bytes(restored) == blob
    assert len(restored.buffer) == 2 and dict(restored.pulled)["c"] == 0
    outs = []
    for twin in (st, restored):
        twin, rep, _ = _push(twin, "c", 6.0, 20, now)
        outs.append((twin.global_blob, twin.model_version, rep.status))
    assert outs[0] == outs[1]
    assert outs[0][1] == 1


def test_orbax_restore_rebases_retained_window(tmp_path):
    """A buffered server resumed from the round-boundary checkpoint must
    key the retained-base window under the RESTORED version — under
    version 0 every post-restart upload would miss the base lookup and
    resync forever."""
    pytest.importorskip("orbax.checkpoint")
    from fedcrack_tpu.ckpt import (
        FedCheckpointer,
        restore_server_state,
        save_server_state,
    )

    cfg = _cfg()
    st = R.initial_state(cfg, _vars(5.0))
    st = st._replace(model_version=3, current_round=4)
    with FedCheckpointer(tmp_path / "ck") as ck:
        save_server_state(ck, st)
        restored = restore_server_state(ck, cfg)
    assert restored is not None and restored.model_version == 3
    assert sorted(restored.base_blobs) == [3]
    assert restored.base_blobs[3] == restored.broadcast_blob


@pytest.mark.chaos
def test_buffered_kill_restart_drill():
    """The scripted gRPC drill: kill mid-buffer, restart over the same
    statefile, flush to the bit-identical next global version."""
    from fedcrack_tpu.tools.chaos_drill import run_buffered_kill_drill

    out = run_buffered_kill_drill()
    assert out["resumed_mid_buffer"]
    assert out["global_blob_bit_identical"]
    assert out["global_version_identical"]


@pytest.mark.chaos
def test_storm_drill_rates_come_from_registry_scrape():
    """Round-15 satellite: the sync-vs-buffered A/B rates are before/after
    deltas of a REAL /metrics scrape, and each arm pins its scraped counts
    against the protocol history — the drill artifact and a dashboard
    watching the same registry can never disagree."""
    from fedcrack_tpu.tools.chaos_drill import run_straggler_storm_drill

    out = run_straggler_storm_drill(seed=0, versions=2)
    assert out["rates_scraped_from_registry"]
    assert out["storm_fired"]
    for arm in ("sync", "buffered"):
        assert out[arm]["scrape_matches_history"], out[arm]
        assert out[arm]["errors"] == []
        assert out[arm]["accepted_updates"] > 0
    assert out["buffered_gt_sync_updates_per_sec"]


# ---------- staleness-aware error feedback ----------

def test_ef_decay_preserves_default_and_scales_residual():
    from fedcrack_tpu.compress import get_codec

    rng = np.random.default_rng(0)
    base = {"params": {"w": rng.normal(size=(64,)).astype(np.float32)}}
    up = {"params": {"w": rng.normal(size=(64,)).astype(np.float32)}}
    b_blob, u_blob = tree_to_bytes(base), tree_to_bytes(up)
    # ef_decay=1.0 is byte-identical to the pre-round-14 encode.
    c_ref = get_codec("topk_delta", topk_fraction=0.1)
    c_one = get_codec("topk_delta", topk_fraction=0.1)
    f_ref = c_ref.encode_update(u_blob, b_blob, round=1, base_version=0)
    f_one = c_one.encode_update(u_blob, b_blob, round=1, base_version=0, ef_decay=1.0)
    assert f_ref == f_one
    assert c_ref.residual_mass() == c_one.residual_mass()
    # ef_decay=w scales the committed residual by exactly w.
    c_dec = get_codec("topk_delta", topk_fraction=0.1)
    c_dec.encode_update(u_blob, b_blob, round=1, base_version=0, ef_decay=0.25)
    assert c_dec.residual_mass() == pytest.approx(0.25 * c_ref.residual_mass())
    with pytest.raises(ValueError):
        c_dec.encode_update(u_blob, b_blob, round=1, base_version=0, ef_decay=1.5)


def test_ef_decay_property_drain():
    """'Nothing lost, only delayed' still converges under sustained decay:
    on a fixed sequence that goes quiet, the decayed accumulator drains to
    zero at least as fast as the classic one, strictly monotonically."""
    from fedcrack_tpu.compress import get_codec

    rng = np.random.default_rng(1)
    base = {"params": {"w": rng.normal(size=(128,)).astype(np.float32)}}
    b_blob = tree_to_bytes(base)
    up = {"params": {"w": (np.asarray(base["params"]["w"]) + rng.normal(size=(128,)).astype(np.float32))}}
    u_blob = tree_to_bytes({"params": {"w": np.asarray(up["params"]["w"], np.float32)}})
    masses = {}
    for decay in (1.0, 0.5):
        codec = get_codec("topk_delta", topk_fraction=0.05)
        codec.encode_update(u_blob, b_blob, round=1, base_version=0, ef_decay=decay)
        series = [codec.residual_mass()]
        for rnd in range(2, 10):
            # The trainer goes quiet (update == base): only the residual
            # re-enters each round.
            codec.encode_update(b_blob, b_blob, round=rnd, base_version=0, ef_decay=decay)
            series.append(codec.residual_mass())
        assert all(b < a for a, b in zip(series, series[1:]))
        masses[decay] = series
    # The decayed series drains at least as fast, every round.
    assert all(d <= u for d, u in zip(masses[0.5], masses[1.0]))
    assert masses[0.5][-1] < 1e-3 * masses[0.5][0] or masses[0.5][-1] < 1e-6


# ---------- edge tier buffered mode ----------

def _edge_template():
    return {"params": {"w": np.zeros((4, 4), np.float32)}}


def test_edge_buffered_flush_weighted_mean():
    from fedcrack_tpu.fed.tree import EdgeAggregator

    base0 = tree_to_bytes(_vars(0.0))
    edge = EdgeAggregator(
        "edge-0", _edge_template(), mode="buffered", buffer_k=2,
        staleness_alpha=1.0, max_staleness=2,
    )
    edge.begin_round(1, base0, 0, ["a", "b", "c"])
    ok, _ = edge.offer_buffered("a", tree_to_bytes(_vars(1.0)), 10, 0)
    assert ok and not edge.buffer_ready()
    # The root advances; b's in-flight update (v0 base) is stale-but-valid.
    base1 = tree_to_bytes(_vars(0.5))
    edge.advance_base(2, base1, 1)
    ok, _ = edge.offer_buffered("b", tree_to_bytes(_vars(3.0)), 30, 0)
    assert ok and edge.buffer_ready()
    blob, total, info = edge.flush_partial()
    # a: eff 10 * (1+1)^-1 = 5 (stale once the base advanced? No — the
    # staleness is stamped at OFFER time: a offered at base_version 0 with
    # edge at 0 (staleness 0, weight 1, eff 10); b offered at edge base 1
    # with base 0 (staleness 1, weight 0.5, eff 15).
    got = tree_from_bytes(blob)["params"]["w"]
    want = (10 * 1.0 * 1.0 + 30 * 0.5 * 3.0) / (10 * 1.0 + 30 * 0.5)
    np.testing.assert_allclose(got, want, atol=1e-6)
    assert total == 25  # round(10 + 15)
    assert info["staleness"] == [0, 1]
    assert not edge.buffer


def test_edge_buffered_rejects_too_stale_and_unretained():
    from fedcrack_tpu.fed.tree import EdgeAggregator

    edge = EdgeAggregator(
        "edge-0", _edge_template(), mode="buffered", buffer_k=2,
        staleness_alpha=0.5, max_staleness=0,
    )
    edge.begin_round(1, tree_to_bytes(_vars(0.0)), 0, ["a", "b"])
    edge.advance_base(2, tree_to_bytes(_vars(1.0)), 1)
    ok, reason = edge.offer_buffered("a", tree_to_bytes(_vars(2.0)), 10, 0)
    assert not ok and "too stale" in reason
    assert "a" in edge.rejected and not edge.buffer
    ok, reason = edge.offer_buffered("b", tree_to_bytes(_vars(2.0)), 10, 5)
    assert not ok and "future" in reason


def test_edge_buffered_statefile_resume(tmp_path):
    from fedcrack_tpu.fed.tree import EdgeAggregator

    path = str(tmp_path / "edge.msgpack")
    base0 = tree_to_bytes(_vars(0.0))
    edge = EdgeAggregator(
        "edge-0", _edge_template(), mode="buffered", buffer_k=2,
        staleness_alpha=1.0, max_staleness=2, state_path=path,
    )
    edge.begin_round(1, base0, 0, ["a", "b", "c"])
    assert edge.offer_buffered("a", tree_to_bytes(_vars(1.0)), 10, 0)[0]
    twin_partial = None
    # Restore WITHOUT the buffered knobs: they must come back from the
    # FILE (a default-argument restore silently changing the flush
    # threshold/decay mid-buffer is the failure being pinned).
    restored = EdgeAggregator.restore(path, _edge_template())
    assert restored is not None and restored.mode == "buffered"
    assert restored.buffer_k == 2
    assert restored.staleness_alpha == 1.0
    assert restored.max_staleness == 2
    assert [e["cname"] for e in restored.buffer] == ["a"]
    assert sorted(restored.bases) == [0]
    for agg in (edge, restored):
        assert agg.offer_buffered("b", tree_to_bytes(_vars(3.0)), 30, 0)[0]
        blob, total, _ = agg.flush_partial()
        if twin_partial is None:
            twin_partial = (blob, total)
        else:
            assert (blob, total) == twin_partial  # bit-identical resume


# ---------- gRPC e2e ----------

@pytest.fixture
def buffered_cfg():
    return FedConfig(
        max_rounds=3,
        cohort_size=2,
        mode="buffered",
        buffer_k=2,
        staleness_alpha=0.5,
        max_staleness=4,
        registration_window_s=5.0,
        poll_period_s=0.05,
        host="127.0.0.1",
        port=0,
    )


def _fake_train(increment: float, samples: int):
    def train_fn(blob: bytes, rnd: int):
        tree = tree_from_bytes(blob)
        tree["params"]["w"] = tree["params"]["w"] + increment
        return tree_to_bytes(tree), samples, {"loss": float(rnd)}

    return train_fn


def test_buffered_grpc_session_two_clients(buffered_cfg):
    """Full buffered session over a real socket: the handshake advertises
    mode=buffered, both FedClients run the continuous pull→train→push
    loop, the server flushes max_rounds global versions, and every flush
    entry carries the async observability fields."""
    from fedcrack_tpu.transport import FedClient, FedServer
    from fedcrack_tpu.transport.service import ServerThread

    server = FedServer(buffered_cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        clients = [
            FedClient(buffered_cfg, _fake_train(1.0, 10), cname="a", port=st.port),
            FedClient(buffered_cfg, _fake_train(3.0, 30), cname="b", port=st.port),
        ]
        results = [None, None]
        threads = [
            threading.Thread(
                target=lambda i=i, c=c: results.__setitem__(i, c.run_session())
            )
            for i, c in enumerate(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        state = st.state

    assert all(r is not None and r.enrolled for r in results)
    assert all(r.final_weights for r in results)
    assert all(r.rounds_completed >= 1 for r in results)
    assert state.phase == R.PHASE_FINISHED
    assert state.model_version == 3
    assert len(state.history) == 3
    for entry in state.history:
        assert entry["mode"] == "buffered"
        assert entry["buffer_fill"] == 2
        assert "staleness" in entry and "updates_per_sec" in entry
    summary = async_summary(state.history)
    assert summary["accepted_updates"] == 6


def test_buffered_grpc_deliberately_stale_client(buffered_cfg):
    """Raw-RPC choreography: a advances the global alone (K=1) while b
    sits on the v0 broadcast; b's late push is accepted stale and
    weighted, visible in the flush history."""
    import dataclasses as dc

    from fedcrack_tpu.tools.chaos_drill import _done, _pull, _raw_caller, _ready
    from fedcrack_tpu.transport import FedServer
    from fedcrack_tpu.transport.service import ServerThread

    cfg = dc.replace(buffered_cfg, buffer_k=1, staleness_alpha=1.0, max_rounds=4)
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    with ServerThread(server) as st:
        channel, call = _raw_caller(st.port)
        assert call(_ready("a")).status == R.SW
        assert call(_ready("b")).status == R.SW
        call(_pull("a"))
        call(_pull("b"))
        assert call(_done("a", 1, 2.0, 10)).status == R.RESP_ARY  # v1
        rep = call(_done("b", 1, 4.0, 10))  # trained on v0: staleness 1
        assert rep.status == R.RESP_ARY
        channel.close()
        state = st.state
    assert state.history[-1]["staleness"] == [1]
    assert state.history[-1]["weights"] == [0.5]


# ---------- async_summary ----------

def test_async_summary_percentiles():
    history = (
        {"buffer_fill": 2, "staleness": [0, 1]},
        {"buffer_fill": 3, "staleness": [0, 2, 4]},
        {"round": 9},  # sync entry: ignored
    )
    out = async_summary(history)
    assert out["accepted_updates"] == 5
    assert out["global_versions"] == 2
    assert out["mean_buffer_fill"] == 2.5
    assert out["staleness"]["max"] == 4.0
    assert out["staleness"]["p50"] == 1.0
