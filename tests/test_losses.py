"""Loss/metric math checks against closed forms."""

import jax
import jax.numpy as jnp
import numpy as np

from fedcrack_tpu.ops import binary_iou, pixel_accuracy, segmentation_metrics, sigmoid_bce
from fedcrack_tpu.ops.losses import iou_counts


def test_bce_matches_manual_form():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 8, 8, 1)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, size=(2, 8, 8, 1)), jnp.float32)
    p = jax.nn.sigmoid(logits)
    manual = -jnp.mean(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
    assert np.allclose(float(sigmoid_bce(logits, labels)), float(manual), atol=1e-5)


def test_bce_stable_at_extreme_logits():
    logits = jnp.asarray([[-80.0, 80.0]])
    labels = jnp.asarray([[0.0, 1.0]])
    val = float(sigmoid_bce(logits, labels))
    assert np.isfinite(val) and val < 1e-6


def test_pixel_accuracy_closed_form():
    logits = jnp.asarray([[10.0, -10.0, 10.0, -10.0]])
    labels = jnp.asarray([[1.0, 0.0, 0.0, 0.0]])
    assert float(pixel_accuracy(logits, labels)) == 0.75


def test_iou_closed_form():
    # preds: [1,1,0,0], labels: [1,0,1,0] -> inter=1, union=3
    logits = jnp.asarray([[10.0, 10.0, -10.0, -10.0]])
    labels = jnp.asarray([[1.0, 0.0, 1.0, 0.0]])
    assert abs(float(binary_iou(logits, labels)) - 1 / 3) < 1e-5


def test_iou_perfect_empty_prediction_scores_one():
    """No crack predicted, none present: 0/0 IoU is a perfect score, not 0."""
    logits = jnp.full((1, 8, 8, 1), -10.0)
    labels = jnp.zeros((1, 8, 8, 1))
    assert float(binary_iou(logits, labels)) == 1.0
    m = segmentation_metrics(logits, labels)
    assert float(m["iou"]) == 1.0


def test_iou_counts_compose_additively_across_shards():
    """Global IoU from summed counts == IoU of the concatenated batch."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 16, 16, 1)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, size=(4, 16, 16, 1)), jnp.float32)
    i_all, u_all = iou_counts(logits, labels)
    i_sum = sum(float(iou_counts(logits[k : k + 1], labels[k : k + 1])[0]) for k in range(4))
    u_sum = sum(float(iou_counts(logits[k : k + 1], labels[k : k + 1])[1]) for k in range(4))
    assert float(i_all) == i_sum and float(u_all) == u_sum


def test_metrics_dict_keys():
    logits = jnp.zeros((1, 4, 4, 1))
    labels = jnp.ones((1, 4, 4, 1))
    m = segmentation_metrics(logits, labels)
    assert set(m) == {"loss", "pixel_acc", "iou", "iou_inter", "iou_union"}


def test_metrics_reduce_in_f32_under_bf16_inputs():
    logits = jnp.zeros((1, 4, 4, 1), jnp.bfloat16)
    labels = jnp.ones((1, 4, 4, 1), jnp.bfloat16)
    m = segmentation_metrics(logits, labels)
    assert m["loss"].dtype == jnp.float32
