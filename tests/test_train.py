"""Training engine: loss decreases, FedProx pulls toward anchor, eval math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.pipeline import ArrayDataset
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.train import (
    create_train_state,
    eval_step,
    evaluate,
    local_fit,
    train_step,
)

CFG32 = ModelConfig(img_size=32)


@pytest.fixture(scope="module")
def fixture_data():
    return synth_crack_batch(16, img_size=32, seed=0)


@pytest.fixture(scope="module")
def state0():
    return create_train_state(jax.random.key(0), CFG32, learning_rate=1e-3)


def test_loss_decreases_on_fixture(state0, fixture_data):
    images, masks = fixture_data
    ds = ArrayDataset(images, masks, batch_size=8, seed=0)
    state, m_first = local_fit(state0, ds, epochs=1)
    state, m_last = local_fit(state, ds, epochs=4)
    assert np.isfinite(m_last["loss"])
    assert m_last["loss"] < m_first["loss"], (m_first, m_last)


def test_train_step_one_program_for_fedavg_and_fedprox(state0, fixture_data):
    """mu is traced: switching FedAvg<->FedProx must not recompile."""
    images, masks = fixture_data
    batch = (jnp.asarray(images[:4]), jnp.asarray(masks[:4]))
    train_step._clear_cache()
    s1, _ = train_step(state0, batch, state0.params, jnp.float32(0.0))
    n_compiles = train_step._cache_size()
    s2, _ = train_step(s1, batch, state0.params, jnp.float32(0.1))
    assert train_step._cache_size() == n_compiles == 1


def test_fedprox_keeps_params_closer_to_anchor(state0, fixture_data):
    images, masks = fixture_data
    batch = (jnp.asarray(images[:8]), jnp.asarray(masks[:8]))
    anchor = state0.params

    def drift(mu):
        s = state0
        for _ in range(5):
            s, _ = train_step(s, batch, anchor, jnp.float32(mu))
        sq = jax.tree_util.tree_map(lambda a, b: jnp.sum((a - b) ** 2), s.params, anchor)
        return float(jax.tree_util.tree_reduce(jnp.add, sq))

    assert drift(mu=100.0) < drift(mu=0.0)


def test_batch_stats_update_during_fit(state0, fixture_data):
    images, masks = fixture_data
    ds = ArrayDataset(images, masks, batch_size=8, seed=0)
    state, _ = local_fit(state0, ds, epochs=1)
    before = jax.tree_util.tree_leaves(state0.batch_stats)
    after = jax.tree_util.tree_leaves(state.batch_stats)
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_eval_step_and_evaluate(state0, fixture_data):
    images, masks = fixture_data
    ds = ArrayDataset(images, masks, batch_size=8, shuffle=False)
    m = eval_step(state0, (jnp.asarray(images[:8]), jnp.asarray(masks[:8])))
    assert np.isfinite(float(m["loss"]))
    agg = evaluate(state0, ds)
    assert set(agg) >= {"loss", "pixel_acc", "iou"}
    assert agg["num_batches"] == 2
    with pytest.raises(ValueError):
        evaluate(state0, [])


def test_centralized_trainer_checkpoints_best(tmp_path, fixture_data):
    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.train.centralized import train_centralized

    images, masks = fixture_data
    train_ds = ArrayDataset(images[:8], masks[:8], batch_size=4, seed=0)
    val_ds = ArrayDataset(images[8:], masks[8:], batch_size=4, shuffle=False)
    state, history = train_centralized(
        train_ds, val_ds, CFG32, epochs=2, out_dir=str(tmp_path), log_fn=lambda s: None
    )
    assert len(history) == 2
    assert (tmp_path / "best.msgpack").exists()
    assert (tmp_path / "final.msgpack").exists()
    restored = tree_from_bytes((tmp_path / "final.msgpack").read_bytes())
    got = jax.tree_util.tree_leaves(restored["params"])
    want = jax.tree_util.tree_leaves(jax.device_get(state.params))
    assert all(np.array_equal(g, w) for g, w in zip(got, want))


def test_make_train_fn_honors_handshake_hparams():
    """Server hparams override the client config: epochs shows up in the
    jitted step count, and a changed lr rebuilds the optimizer."""
    import numpy as np

    from fedcrack_tpu.configs import DataConfig, FedConfig, ModelConfig
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.train.federated import make_train_fn

    cfg = FedConfig(
        local_epochs=1,
        model=ModelConfig(img_size=32),
        data=DataConfig(img_size=32, batch_size=4),
    )
    images, masks = synth_crack_batch(8, img_size=32, seed=0)
    dataset = ArrayDataset(images, masks, batch_size=4, seed=0)
    train_fn, holder = make_train_fn(cfg, dataset, batch_size=4, seed=0)
    blob = tree_to_bytes(holder["state"].variables)

    train_fn(blob, 1, {"local_epochs": 3, "learning_rate": 0.01, "fedprox_mu": 0.0})
    # 3 epochs x (8 samples / batch 4) = 6 jitted steps
    assert int(holder["state"].step) == 6
    assert holder["learning_rate"] == 0.01

    # no hparams -> client defaults (1 epoch, 2 more steps)
    train_fn(blob, 2)
    assert int(holder["state"].step) == 8
