"""Training engine: loss decreases, FedProx pulls toward anchor, eval math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.pipeline import ArrayDataset
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.train import (
    create_train_state,
    eval_step,
    evaluate,
    local_fit,
    train_step,
)

CFG32 = ModelConfig(img_size=32)


@pytest.fixture(scope="module")
def fixture_data():
    return synth_crack_batch(16, img_size=32, seed=0)


@pytest.fixture(scope="module")
def state0():
    return create_train_state(jax.random.key(0), CFG32, learning_rate=1e-3)


def test_loss_decreases_on_fixture(state0, fixture_data):
    images, masks = fixture_data
    ds = ArrayDataset(images, masks, batch_size=8, seed=0)
    state, m_first = local_fit(state0, ds, epochs=1)
    state, m_last = local_fit(state, ds, epochs=4)
    assert np.isfinite(m_last["loss"])
    assert m_last["loss"] < m_first["loss"], (m_first, m_last)


def test_train_step_one_program_for_fedavg_and_fedprox(state0, fixture_data):
    """mu is traced: switching FedAvg<->FedProx must not recompile."""
    images, masks = fixture_data
    batch = (jnp.asarray(images[:4]), jnp.asarray(masks[:4]))
    train_step._clear_cache()
    s1, _ = train_step(state0, batch, state0.params, jnp.float32(0.0))
    n_compiles = train_step._cache_size()
    s2, _ = train_step(s1, batch, state0.params, jnp.float32(0.1))
    assert train_step._cache_size() == n_compiles == 1


# Tier-1 budget re-balance (round 13, r4/r9/r12 precedent): ~24 s of two
# full fixture fits whose semantics stay tier-1 elsewhere — the proximal
# penalty's closed form in test_fed::test_fedprox_penalty_closed_form and
# the mu-argument plumbing in test_train_step_one_program_for_fedavg_and_
# fedprox above. The drift-comparison property still runs in the slow suite.
@pytest.mark.slow
def test_fedprox_keeps_params_closer_to_anchor(state0, fixture_data):
    images, masks = fixture_data
    batch = (jnp.asarray(images[:8]), jnp.asarray(masks[:8]))
    anchor = state0.params

    def drift(mu):
        s = state0
        for _ in range(5):
            s, _ = train_step(s, batch, anchor, jnp.float32(mu))
        sq = jax.tree_util.tree_map(lambda a, b: jnp.sum((a - b) ** 2), s.params, anchor)
        return float(jax.tree_util.tree_reduce(jnp.add, sq))

    assert drift(mu=100.0) < drift(mu=0.0)


def test_batch_stats_update_during_fit(state0, fixture_data):
    images, masks = fixture_data
    ds = ArrayDataset(images, masks, batch_size=8, seed=0)
    state, _ = local_fit(state0, ds, epochs=1)
    before = jax.tree_util.tree_leaves(state0.batch_stats)
    after = jax.tree_util.tree_leaves(state.batch_stats)
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_eval_step_and_evaluate(state0, fixture_data):
    images, masks = fixture_data
    ds = ArrayDataset(images, masks, batch_size=8, shuffle=False)
    m = eval_step(state0, (jnp.asarray(images[:8]), jnp.asarray(masks[:8])))
    assert np.isfinite(float(m["loss"]))
    agg = evaluate(state0, ds)
    assert set(agg) >= {"loss", "pixel_acc", "iou"}
    assert agg["num_batches"] == 2
    with pytest.raises(ValueError):
        evaluate(state0, [])


def test_centralized_trainer_checkpoints_best(tmp_path, fixture_data):
    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.train.centralized import train_centralized

    images, masks = fixture_data
    train_ds = ArrayDataset(images[:8], masks[:8], batch_size=4, seed=0)
    val_ds = ArrayDataset(images[8:], masks[8:], batch_size=4, shuffle=False)
    state, history = train_centralized(
        train_ds, val_ds, CFG32, epochs=2, out_dir=str(tmp_path), log_fn=lambda s: None
    )
    assert len(history) == 2
    assert (tmp_path / "best.msgpack").exists()
    assert (tmp_path / "final.msgpack").exists()
    restored = tree_from_bytes((tmp_path / "final.msgpack").read_bytes())
    got = jax.tree_util.tree_leaves(restored["params"])
    want = jax.tree_util.tree_leaves(jax.device_get(state.params))
    assert all(np.array_equal(g, w) for g, w in zip(got, want))


# Tier-1 budget re-balance (round 13): ~15 s of a full centralized fit for
# the JSONL/TB teeing only — the sinks themselves are tier-1-pinned in
# test_obs, and the centralized trainer's training/checkpoint semantics in
# test_centralized_trainer_checkpoints_best. Still runs in the slow suite.
@pytest.mark.slow
def test_centralized_trainer_emits_structured_metrics(tmp_path):
    """The centralized entry point tees per-epoch records to JSONL + real
    TensorBoard event files, like the federated entry points (the
    reference's TB-per-fit workflow, client_fit_model.py:153-154)."""
    import glob

    from fedcrack_tpu.obs import MetricsLogger, read_metrics, read_scalars
    from fedcrack_tpu.train.centralized import train_centralized

    images, masks = synth_crack_batch(12, 32, seed=4)
    train_ds = ArrayDataset(images[:8], masks[:8], batch_size=4, seed=0)
    val_ds = ArrayDataset(images[8:], masks[8:], batch_size=4, shuffle=False)
    jsonl = tmp_path / "m.jsonl"
    tb = tmp_path / "tb"
    logger = MetricsLogger(jsonl, tb_dir=tb)
    train_centralized(
        train_ds, val_ds, CFG32, epochs=2, log_fn=lambda s: None, metrics=logger
    )
    logger.close()
    records = [r for r in read_metrics(jsonl) if r["kind"] == "epoch"]
    assert [r["epoch"] for r in records] == [0, 1]
    assert all("val_iou" in r and "train_loss" in r for r in records)
    event_files = glob.glob(str(tb / "events.out.tfevents.*"))
    assert event_files, "no TB event file written"
    tags = {t for t, _, _ in read_scalars(event_files[0])}
    assert any("val_loss" in t for t in tags), tags


@pytest.mark.slow
def test_centralized_reaches_iou_floor():
    """The framework must SEGMENT CRACKS, not just minimize a scalar: the
    centralized trainer (reference: test/Segmentation.py, quality-gated by
    val checkpointing at :177-186) on the synthetic fixture must localize
    cracks to val IoU >= 0.2 within 12 epochs. Measured headroom: ~0.27-0.28
    final IoU at this config (64px, 64 train / 16 val, pos_weight 5); a
    regression in the model, loss, data pipeline, BN handling, or recalibration
    pulls this under the floor."""
    from fedcrack_tpu.train.centralized import train_centralized

    cfg = ModelConfig(img_size=64)
    images, masks = synth_crack_batch(80, 64, seed=0)
    train_ds = ArrayDataset(images[:64], masks[:64], batch_size=8, seed=0)
    val_ds = ArrayDataset(images[64:], masks[64:], batch_size=8, shuffle=False)
    _, history = train_centralized(
        train_ds,
        val_ds,
        cfg,
        epochs=12,
        learning_rate=1e-3,
        pos_weight=5.0,
        log_fn=lambda s: None,
    )
    ious = [h["val_iou"] for h in history]
    assert ious[-1] >= 0.2, f"final val IoU {ious[-1]:.3f} under the 0.2 floor: {ious}"
    # and learning actually progressed (not a lucky init)
    assert ious[-1] > ious[0] + 0.05, ious


@pytest.mark.slow
def test_centralized_reaches_iou_half_on_thick_fixture():
    """Absolute quality bar (round-3 verdict #5): val IoU >= 0.5. The
    hairline parity fixture is boundary-dominated (measured 40-epoch
    ceiling ~0.38, bench_runs/r03_quality_posweight_64px.json), so this
    gate uses a thicker crack stroke where 0.5 separates real localization
    from luck. Calibrated headroom: IoU 0.60-0.65 from epoch 10 of this
    exact config (bench_runs/r03_quality_gate_calibration.json)."""
    from fedcrack_tpu.train.centralized import train_centralized

    cfg = ModelConfig(img_size=64)
    images, masks = synth_crack_batch(160, 64, seed=0, min_thickness=3)
    train_ds = ArrayDataset(images[:128], masks[:128], batch_size=8, seed=0)
    val_ds = ArrayDataset(images[128:], masks[128:], batch_size=8, shuffle=False)
    _, history = train_centralized(
        train_ds,
        val_ds,
        cfg,
        epochs=12,
        learning_rate=1e-3,
        pos_weight=5.0,
        log_fn=lambda s: None,
    )
    ious = [h["val_iou"] for h in history]
    assert ious[-1] >= 0.5, f"final val IoU {ious[-1]:.3f} under the 0.5 floor: {ious}"


@pytest.mark.slow
def test_federated_reaches_absolute_iou_floor():
    """The FEDERATED path carries its own absolute quality floor (round-3
    verdict #5 — previously only round-over-round improvement was gated):
    2 real clients x 3 rounds x 3 local epochs on the thick-stroke fixture
    must land the aggregated global model at held-out IoU >= 0.35
    (calibrated: rounds measured 0.42 / 0.50 / 0.48,
    bench_runs/r03_quality_gate_calibration.json)."""
    import dataclasses
    import threading

    from fedcrack_tpu.configs import DataConfig, FedConfig
    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.train.federated import make_train_fn
    from fedcrack_tpu.train.local import recalibrate_batch_stats
    from fedcrack_tpu.transport.client import FedClient
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    cfg = FedConfig(
        max_rounds=3,
        cohort_size=2,
        local_epochs=3,
        pos_weight=5.0,
        registration_window_s=10.0,
        poll_period_s=0.2,
        port=0,
        model=ModelConfig(img_size=64),
        data=DataConfig(img_size=64, batch_size=8),
    )
    ev_i, ev_m = synth_crack_batch(32, 64, seed=999, min_thickness=3)
    eval_ds = ArrayDataset(ev_i, ev_m, batch_size=8, shuffle=False, drop_last=False)
    tmpl = create_train_state(jax.random.key(0), cfg.model)

    server = FedServer(cfg, tmpl.variables, tick_period_s=0.1)
    with ServerThread(server) as st:
        def run(i):
            imgs, msks = synth_crack_batch(48, 64, seed=10 + i, min_thickness=3)
            ds = ArrayDataset(imgs, msks, batch_size=8, seed=i)
            fn, _ = make_train_fn(cfg, ds, batch_size=8, seed=i)
            FedClient(cfg, fn, cname=f"c{i}", port=st.port).run_session()

        threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1800)
        final_blob = st.state.global_blob
        assert st.state.current_round > cfg.max_rounds

    st_model = tmpl.replace_variables(
        tree_from_bytes(final_blob, template=tmpl.variables)
    )
    st_model = recalibrate_batch_stats(st_model, eval_ds, cfg.model)
    m = evaluate(st_model, eval_ds, pos_weight=5.0)
    assert m["iou"] >= 0.35, f"federated held-out IoU {m['iou']:.3f} under the 0.35 floor"


# Tier-1 budget re-balance (round 13): ~20 s (short fit + recalibration
# pass). Quality machinery, no protocol semantics; the BN-momentum parity
# itself is pinned cheaply in test_model. Still runs in the slow suite.
@pytest.mark.slow
def test_recalibrate_batch_stats_fixes_eval_mode():
    """Keras-parity BN momentum (0.99) leaves running stats near init after a
    short fit, collapsing inference-mode predictions; recalibration must
    recover eval-mode quality to (approximately) train-mode levels."""
    from fedcrack_tpu.train import recalibrate_batch_stats

    images, masks = synth_crack_batch(16, 32, seed=0)
    ds = ArrayDataset(images, masks, batch_size=8, seed=0)
    state = create_train_state(jax.random.key(0), CFG32, learning_rate=1e-3)
    state, _ = local_fit(state, ds, epochs=4, pos_weight=5.0)
    stale = evaluate(state, ds, pos_weight=5.0)
    cal = recalibrate_batch_stats(state, ds, CFG32)
    fresh = evaluate(cal, ds, pos_weight=5.0)
    # params untouched; only batch_stats move
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params), jax.tree_util.tree_leaves(cal.params)
    ):
        assert np.array_equal(a, b)
    # The collapse this test exists to catch is an all-background predictor
    # (near-init running stats -> zero crack recall). Pin the mechanism on
    # SEGMENTATION quality: an all-background model scores IoU 0 however
    # its BCE scalar lands (background dominates ~93% of pixels, so the
    # loss ordering at this 8-step toy scale is backend-trajectory luck —
    # it flipped between XLA versions while IoU told the same story).
    assert fresh["iou"] > stale["iou"], (stale, fresh)
    assert fresh["iou"] > 0.1, (stale, fresh)
    # calibration must not advance the dataset's shuffle epoch — a seeded
    # run has to reproduce identically with calibration on or off
    epoch_before = ds._epoch
    recalibrate_batch_stats(state, ds, CFG32)
    assert ds._epoch == epoch_before
    with pytest.raises(ValueError):
        recalibrate_batch_stats(state, [], CFG32)


# Tier-1 budget re-balance (round 14, r4/r9/r12/r13 precedent): the
# hparams-ride-the-handshake contract stays tier-1 at the transport level
# (test_transport::test_handshake_hyperparameters_reach_trainer); this is
# the REAL-trainer twin (~19 s of extra compiles).
@pytest.mark.slow
def test_make_train_fn_honors_handshake_hparams():
    """Server hparams override the client config: epochs shows up in the
    jitted step count, and a changed lr rebuilds the optimizer."""
    import numpy as np

    from fedcrack_tpu.configs import DataConfig, FedConfig, ModelConfig
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.train.federated import make_train_fn

    cfg = FedConfig(
        local_epochs=1,
        model=ModelConfig(img_size=32),
        data=DataConfig(img_size=32, batch_size=4),
    )
    images, masks = synth_crack_batch(8, img_size=32, seed=0)
    dataset = ArrayDataset(images, masks, batch_size=4, seed=0)
    train_fn, holder = make_train_fn(cfg, dataset, batch_size=4, seed=0)
    blob = tree_to_bytes(holder["state"].variables)

    train_fn(blob, 1, {"local_epochs": 3, "learning_rate": 0.01, "fedprox_mu": 0.0})
    # 3 epochs x (8 samples / batch 4) = 6 jitted steps
    assert int(holder["state"].step) == 6
    assert holder["learning_rate"] == 0.01

    # no hparams -> client defaults (1 epoch, 2 more steps)
    train_fn(blob, 2)
    assert int(holder["state"].step) == 8


# Tier-1 budget re-balance (round 13): ~12 s of a full client fit for the
# histogram teeing only; the TB writer's histogram encoding is tier-1 in
# test_obs and make_train_fn's training semantics in the handshake-hparams
# test above. Still runs in the slow suite.
@pytest.mark.slow
def test_make_train_fn_tees_weight_histograms(tmp_path):
    """With a TB-enabled metrics logger, each round's local fit emits
    per-layer weight AND round-update (trained minus received params)
    histograms — the reference's histogram_freq=1 callback
    (client_fit_model.py:153-154)."""
    import glob

    from fedcrack_tpu.configs import DataConfig, FedConfig, ModelConfig
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.obs import MetricsLogger, read_histograms
    from fedcrack_tpu.train.federated import make_train_fn

    cfg = FedConfig(
        local_epochs=1,
        model=ModelConfig(img_size=32),
        data=DataConfig(img_size=32, batch_size=4),
    )
    images, masks = synth_crack_batch(8, img_size=32, seed=0)
    dataset = ArrayDataset(images, masks, batch_size=4, seed=0)
    logger = MetricsLogger(tmp_path / "m.jsonl", tb_dir=tmp_path / "tb")
    train_fn, holder = make_train_fn(
        cfg, dataset, batch_size=4, seed=0, metrics_logger=logger
    )
    blob = tree_to_bytes(holder["state"].variables)
    train_fn(blob, 1)
    logger.close()

    (event_file,) = glob.glob(str(tmp_path / "tb" / "events.out.tfevents.*"))
    got = read_histograms(event_file)
    tags = {t for t, _, _ in got}
    assert any(t.startswith("weights/") and t.endswith("kernel") for t in tags), tags
    assert any(t.startswith("round_update/") for t in tags), tags
    # every histogram is pinned to the round and structurally sound
    for tag, h, step in got:
        assert step == 1
        assert len(h["bucket"]) == len(h["bucket_limit"])
        assert sum(h["bucket"]) == h["num"]
    # a trained param actually moved: its update histogram is not all-zero
    updates = [h for t, h, _ in got if t.startswith("round_update/")]
    assert any(h["min"] < 0 or h["max"] > 0 for h in updates)
