"""End-to-end: 2 real U-Net clients federate over localhost gRPC.

This is SURVEY.md §7's "minimum slice B" (BASELINE.md config 2) shrunk for
CI: real Flax model, real jitted local fit, real msgpack weights on the wire,
real FedAvg rounds — tiny shapes (32px, 8 imgs/client, 1 local epoch,
2 rounds)."""

import dataclasses
import threading

import numpy as np
import pytest

from fedcrack_tpu.configs import DataConfig, FedConfig, ModelConfig
from fedcrack_tpu.data.pipeline import ArrayDataset
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes
from fedcrack_tpu.train.federated import make_train_fn
from fedcrack_tpu.transport import FedClient, FedServer
from fedcrack_tpu.transport.service import ServerThread


@pytest.mark.slow
def test_two_real_clients_federate():
    cfg = FedConfig(
        max_rounds=3,
        cohort_size=2,
        local_epochs=2,
        pos_weight=5.0,  # crack-pixel weighting so 3 tiny rounds show real IoU motion
        registration_window_s=10.0,
        poll_period_s=0.1,
        host="127.0.0.1",
        port=0,
        model=ModelConfig(img_size=32),
        data=DataConfig(img_size=32, batch_size=4),
    )

    def make_client(name: str, seed: int):
        images, masks = synth_crack_batch(8, 32, seed=seed)
        ds = ArrayDataset(images, masks, batch_size=4, seed=seed)
        train_fn, holder = make_train_fn(cfg, ds, batch_size=4, seed=seed)
        return FedClient(cfg, train_fn, cname=name), holder

    import jax

    from fedcrack_tpu.train.local import create_train_state

    server_state0 = create_train_state(jax.random.key(0), cfg.model)
    server = FedServer(cfg, server_state0.variables, tick_period_s=0.1)

    with ServerThread(server) as st:
        cfg_bound = dataclasses.replace(cfg, port=st.port)
        results = {}

        def run(name, seed):
            client, _ = make_client(name, seed)
            client.port = st.port
            results[name] = client.run_session()

        threads = [
            threading.Thread(target=run, args=("a", 1)),
            threading.Thread(target=run, args=("b", 2)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        state = st.state

    assert state.phase == R.PHASE_FINISHED
    assert len(state.history) == cfg.max_rounds
    for name in ("a", "b"):
        r = results[name]
        assert r.enrolled and r.rounds_completed == cfg.max_rounds
        assert all(np.isfinite(h["loss"]) for h in r.history)

    # round-over-round learning: the federation must IMPROVE crack IoU, not
    # just move weights (SURVEY.md §4 "IoU above a floor"; the reference's
    # only oracle was a val-loss checkpoint, test/Segmentation.py:177-186).
    # Train-mode IoU of each client's final local epoch, per round:
    for name in ("a", "b"):
        ious = [
            h["iou_inter"] / max(h["iou_union"], 1.0) for h in results[name].history
        ]
        assert ious[-1] > ious[0], f"{name}: no IoU improvement across rounds: {ious}"

    # the broadcast final weights equal the server's global average
    final = tree_from_bytes(state.global_blob)
    for name in ("a", "b"):
        client_final = tree_from_bytes(results[name].final_weights)
        for lc, ls in zip(_leaves(client_final), _leaves(final)):
            assert np.allclose(lc, ls, atol=1e-6)

    # the global model actually moved away from its initialization
    init_leaves = _leaves(server_state0.variables["params"])
    final_leaves = _leaves(final["params"])
    assert any(
        not np.allclose(i, f, atol=1e-7) for i, f in zip(init_leaves, final_leaves)
    )


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


@pytest.mark.slow
def test_composed_production_stack(tmp_path):
    """The COMPOSED production configuration in ONE run (round-3 verdict
    item 5) — each piece is tested in isolation elsewhere; this is the
    full-system path: file-based ``CrackDataset`` (real JPEG decode), uint8
    transport, TLS + token auth, server checkpointing, the server KILLED and
    RESTARTED mid-federation (clients restart and rejoin — the reference's
    operator flow, fl_client.py:178-188), the federation completing with the
    round counter/history/weights carried across the restart, final held-out
    IoU above the calibrated 0.35 floor, and TensorBoard logs uploaded
    through the chunked 'L' sink."""
    import glob
    import os
    import time

    import jax

    from fedcrack_tpu.ckpt import FedCheckpointer
    from fedcrack_tpu.data.pipeline import CrackDataset, list_pairs
    from fedcrack_tpu.data.synthetic import write_synthetic_dataset
    from fedcrack_tpu.obs.tb import SummaryWriter, read_scalars
    from fedcrack_tpu.train.local import (
        create_train_state,
        evaluate,
        recalibrate_batch_stats,
    )
    from test_transport import _self_signed_cert  # importorskips cryptography

    pytest.importorskip("cv2")  # the on-disk fixture writer needs an encoder
    cert, key = _self_signed_cert(tmp_path)
    n_clients, img, batch = 2, 64, 8

    cfg = FedConfig(
        max_rounds=3,
        cohort_size=n_clients,
        local_epochs=3,
        pos_weight=5.0,
        registration_window_s=10.0,
        poll_period_s=0.2,
        host="127.0.0.1",
        port=0,
        auth_token="prod-tøken",  # non-ASCII: utf-8 token path
        tls_cert=cert,
        tls_key=key,
        tls_ca=cert,  # self-signed: the cert is its own root
        ckpt_dir=str(tmp_path / "ckpt"),
        logs_dir=str(tmp_path / "server_logs"),
        model=ModelConfig(img_size=img),
        data=DataConfig(img_size=img, batch_size=batch),
    )

    # File-based local shards: real JPEGs + PNG masks on disk, thick-stroke
    # quality-gate geometry, decoded through the production pipeline with
    # uint8 transport to the device.
    datasets, log_paths = {}, {}
    for i in range(n_clients):
        img_dir, mask_dir = write_synthetic_dataset(
            str(tmp_path / f"shard{i}"), n=48, img_size=img, seed=10 + i,
            min_thickness=3,
        )
        datasets[i] = CrackDataset(
            list_pairs(img_dir, mask_dir),
            img_size=img,
            batch_size=batch,
            seed=i,
            num_workers=2,
            transport_dtype="uint8",
        )
        # A real TB event file per client, shipped post-FIN via the 'L' path.
        logdir = tmp_path / f"tb{i}"
        with SummaryWriter(logdir) as w:
            w.add_scalar("train/loss", 1.0 - 0.1 * i, step=1)
        log_paths[i] = glob.glob(str(logdir / "events.out.tfevents.*"))[0]

    tmpl = create_train_state(jax.random.key(0), cfg.model)
    results: dict = {}

    def client_thread(i, attempt, port):
        def run():
            train_fn, _ = make_train_fn(cfg, datasets[i], batch_size=batch, seed=i)
            # Short RPC deadlines: with the default 300 s call timeout a
            # wait_for_ready call against the killed server would block the
            # phase-A join for minutes x max_retries.
            c = FedClient(
                cfg,
                train_fn,
                cname=f"c{i}",
                port=port,
                upload_paths=[log_paths[i]],
                max_retries=2,
                call_timeout_s=15.0,
            )
            try:
                results[(i, attempt)] = c.run_session()
            except Exception as e:  # expected for attempt 1: the server dies
                results[(i, attempt)] = e

        t = threading.Thread(target=run)
        t.start()
        return t

    # ---- phase A: server with checkpointing; killed after round 1 closes ----
    with FedCheckpointer(cfg.ckpt_dir) as ckptr1:
        server1 = FedServer(cfg, tmpl.variables, tick_period_s=0.1, checkpointer=ckptr1)
        with ServerThread(server1) as st1:
            threads = [client_thread(i, 1, st1.port) for i in range(n_clients)]
            # Kill only once round 1 has closed AND its checkpoint is on
            # disk — the save runs off-loop, and killing inside that window
            # would test a lost checkpoint, not a resume.
            deadline = time.time() + 900
            while time.time() < deadline and (
                len(st1.state.history) < 1 or ckptr1.latest_version() is None
            ):
                time.sleep(0.5)
            state_a = st1.state
            assert len(state_a.history) >= 1, "round 1 never closed"
            assert ckptr1.latest_version() is not None, "round 1 never checkpointed"
            assert state_a.phase != R.PHASE_FINISHED, (
                "federation finished before the kill — nothing left to resume"
            )
        # server process "crashed" here (ServerThread exited); the clients'
        # next RPC fails after their retry budget and their sessions error out
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), (
            "a phase-A client is still running 300 s after the server died — "
            "it would leak into phase B"
        )
        rounds_done_a = len(state_a.history)
    for i in range(n_clients):
        assert isinstance(results[(i, 1)], Exception), (
            f"client {i} survived the server crash: {results[(i, 1)]}"
        )

    # ---- phase B: restarted server resumes from the checkpoint ----
    with FedCheckpointer(cfg.ckpt_dir) as ckptr2:
        server2 = FedServer(cfg, tmpl.variables, tick_period_s=0.1, checkpointer=ckptr2)
        # Resume semantics: round counter/version/history restored, enrollment
        # re-opened for the restarted cohort (ckpt/manager.restore_server_state).
        # (>= because another round may close between the history poll and the
        # actual server stop.)
        assert len(server2.state.history) >= rounds_done_a
        assert server2.state.current_round == len(server2.state.history) + 1
        with ServerThread(server2) as st2:
            threads = [client_thread(i, 2, st2.port) for i in range(n_clients)]
            for t in threads:
                t.join(timeout=900)
            state_b = st2.state

    # The federation COMPLETED across the restart: all rounds in one history.
    assert state_b.phase == R.PHASE_FINISHED
    assert len(state_b.history) == cfg.max_rounds
    for i in range(n_clients):
        r = results[(i, 2)]
        assert not isinstance(r, Exception), f"client {i} rejoin failed: {r}"
        assert r.enrolled and r.rounds_completed == cfg.max_rounds

    # Quality floor on the final aggregated model (BN-recalibrated held-out
    # eval at the training pos_weight — same calibration as
    # test_train.py::test_federated_reaches_absolute_iou_floor).
    ev_i, ev_m = synth_crack_batch(32, img, seed=999, min_thickness=3)
    eval_ds = ArrayDataset(ev_i, ev_m, batch_size=batch, shuffle=False, drop_last=False)
    final = tree_from_bytes(state_b.global_blob, template=tmpl.variables)
    st_model = tmpl.replace_variables(final)
    st_model = recalibrate_batch_stats(st_model, eval_ds, cfg.model)
    m = evaluate(st_model, eval_ds, pos_weight=cfg.pos_weight)
    assert m["iou"] >= 0.35, (
        f"composed-stack held-out IoU {m['iou']:.3f} under the 0.35 floor"
    )

    # Logs landed in the server's sink (namespaced per client, path
    # sanitized), byte-for-byte, and still parse as TensorBoard events.
    for i in range(n_clients):
        sunk = os.path.join(cfg.logs_dir, f"c{i}", os.path.basename(log_paths[i]))
        assert os.path.exists(sunk), f"client {i} log never reached the sink"
        with open(log_paths[i], "rb") as f_src, open(sunk, "rb") as f_dst:
            assert f_src.read() == f_dst.read()
        tags = {t for t, _, _ in read_scalars(sunk)}
        assert "train/loss" in tags
