"""End-to-end: 2 real U-Net clients federate over localhost gRPC.

This is SURVEY.md §7's "minimum slice B" (BASELINE.md config 2) shrunk for
CI: real Flax model, real jitted local fit, real msgpack weights on the wire,
real FedAvg rounds — tiny shapes (32px, 8 imgs/client, 1 local epoch,
2 rounds)."""

import dataclasses
import threading

import numpy as np
import pytest

from fedcrack_tpu.configs import DataConfig, FedConfig, ModelConfig
from fedcrack_tpu.data.pipeline import ArrayDataset
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes
from fedcrack_tpu.train.federated import make_train_fn
from fedcrack_tpu.transport import FedClient, FedServer
from fedcrack_tpu.transport.service import ServerThread


@pytest.mark.slow
def test_two_real_clients_federate():
    cfg = FedConfig(
        max_rounds=3,
        cohort_size=2,
        local_epochs=2,
        pos_weight=5.0,  # crack-pixel weighting so 3 tiny rounds show real IoU motion
        registration_window_s=10.0,
        poll_period_s=0.1,
        host="127.0.0.1",
        port=0,
        model=ModelConfig(img_size=32),
        data=DataConfig(img_size=32, batch_size=4),
    )

    def make_client(name: str, seed: int):
        images, masks = synth_crack_batch(8, 32, seed=seed)
        ds = ArrayDataset(images, masks, batch_size=4, seed=seed)
        train_fn, holder = make_train_fn(cfg, ds, batch_size=4, seed=seed)
        return FedClient(cfg, train_fn, cname=name), holder

    import jax

    from fedcrack_tpu.train.local import create_train_state

    server_state0 = create_train_state(jax.random.key(0), cfg.model)
    server = FedServer(cfg, server_state0.variables, tick_period_s=0.1)

    with ServerThread(server) as st:
        cfg_bound = dataclasses.replace(cfg, port=st.port)
        results = {}

        def run(name, seed):
            client, _ = make_client(name, seed)
            client.port = st.port
            results[name] = client.run_session()

        threads = [
            threading.Thread(target=run, args=("a", 1)),
            threading.Thread(target=run, args=("b", 2)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        state = st.state

    assert state.phase == R.PHASE_FINISHED
    assert len(state.history) == cfg.max_rounds
    for name in ("a", "b"):
        r = results[name]
        assert r.enrolled and r.rounds_completed == cfg.max_rounds
        assert all(np.isfinite(h["loss"]) for h in r.history)

    # round-over-round learning: the federation must IMPROVE crack IoU, not
    # just move weights (SURVEY.md §4 "IoU above a floor"; the reference's
    # only oracle was a val-loss checkpoint, test/Segmentation.py:177-186).
    # Train-mode IoU of each client's final local epoch, per round:
    for name in ("a", "b"):
        ious = [
            h["iou_inter"] / max(h["iou_union"], 1.0) for h in results[name].history
        ]
        assert ious[-1] > ious[0], f"{name}: no IoU improvement across rounds: {ious}"

    # the broadcast final weights equal the server's global average
    final = tree_from_bytes(state.global_blob)
    for name in ("a", "b"):
        client_final = tree_from_bytes(results[name].final_weights)
        for lc, ls in zip(_leaves(client_final), _leaves(final)):
            assert np.allclose(lc, ls, atol=1e-6)

    # the global model actually moved away from its initialization
    init_leaves = _leaves(server_state0.variables["params"])
    final_leaves = _leaves(final["params"])
    assert any(
        not np.allclose(i, f, atol=1e-7) for i, f in zip(init_leaves, final_leaves)
    )


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)
