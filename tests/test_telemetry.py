"""Round-15 telemetry plane: registry, /metrics exposition, spans, sentries,
cross-replica percentile merge — and the concurrent mini-soak.

The load-bearing claims, each pinned here:

- the metric registry is get-or-create (same family twice), type/label
  mismatches are loud, names are validated against the OBS001 catalog
  contract at runtime;
- exposition is DETERMINISTIC: two registries holding the same values —
  populated in different orders — expose byte-identical Prometheus text;
- the full loop closes over REAL HTTP: expose -> GET /metrics -> parse ->
  the same numbers (the parse round-trip the acceptance criteria name);
- ``StreamingPercentiles.merge`` equals numpy percentiles of the pooled
  samples while the combined stream fits capacity (property-tested across
  seeds/splits), keeps count/sum/min/max EXACT past capacity, and is
  deterministic for a given (seed, call sequence);
- spans correlate: trace ids + parent ids thread through nested work and
  the JSONL records carry monotonic durations;
- leak sentries trip on growth past slack and stay quiet under it;
- the mini-soak (every plane at once, chaos rolling, self-scraped) ends
  with a CLEAN invariant audit — tier-1 runs a short wall, the 60-second
  version is slow-marked.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from fedcrack_tpu.obs.metrics import StreamingPercentiles
from fedcrack_tpu.obs.promexp import (
    CONTENT_TYPE,
    MetricsExporter,
    parse_prometheus_text,
    sample_value,
    scrape,
)
from fedcrack_tpu.obs.registry import MetricsRegistry, validate_metric_name
from fedcrack_tpu.obs import sentries, spans as tracing


# ---- registry ----


def test_registry_get_or_create_and_mismatches_are_loud():
    reg = MetricsRegistry()
    c1 = reg.counter("fed_updates_total", "updates", labels=("result",))
    c2 = reg.counter("fed_updates_total", "updates", labels=("result",))
    assert c1 is c2
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.gauge("fed_updates_total")
    with pytest.raises(ValueError, match="labels"):
        reg.counter("fed_updates_total", labels=("reason",))
    h = reg.histogram("fed_flush_seconds", buckets=(0.1, 1.0))
    assert reg.histogram("fed_flush_seconds") is h  # buckets=None matches
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("fed_flush_seconds", buckets=(0.5, 1.0))


def test_registry_name_validation_is_the_obs001_contract():
    for bad in ("FedUpdates_total", "updates", "updates_count", "9_total"):
        with pytest.raises(ValueError):
            validate_metric_name(bad)
    for good in (
        "fed_updates_total", "serve_request_seconds", "edge_wire_bytes",
        "fed_buffer_fill_ratio", "fed_update_staleness_versions",
    ):
        assert validate_metric_name(good) == good
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="unit suffix"):
        reg.counter("updates_count")
    with pytest.raises(ValueError, match="bad label name"):
        reg.counter("x_total", labels=("le",))


def test_counter_monotone_gauge_free_histogram_cumulative():
    reg = MetricsRegistry()
    c = reg.counter("x_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    g = reg.gauge("q_ratio")
    g.set(2.0)
    g.dec(0.5)
    assert g.value == 1.5
    g.set_function(lambda: 42.0)
    assert g.value == 42.0
    g.set_function(lambda: 1 / 0)  # a raising callback reads as NaN
    assert np.isnan(g.value)
    h = reg.histogram("w_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5 and snap["sum"] == pytest.approx(56.05)
    # Cumulative: le=0.1 -> 1, le=1.0 -> 3, le=10.0 -> 4, +Inf -> 5.
    assert [cum for _, cum in snap["buckets"]] == [1, 3, 4, 5]


def _populate(reg: MetricsRegistry, order: list[str]):
    """Build the same state through any creation/update order."""
    ops = {
        "a": lambda: reg.counter("fed_updates_total", "u", labels=("result",))
        .labels(result="accepted").inc(7),
        "b": lambda: reg.counter("fed_updates_total", "u", labels=("result",))
        .labels(result="rejected_stale").inc(2),
        "c": lambda: reg.gauge("fed_buffer_fill_ratio", "fill").set(0.5),
        "d": lambda: [
            reg.histogram("serve_request_seconds", "lat", buckets=(0.1, 1.0))
            .observe(v) for v in (0.05, 0.2, 3.0)
        ],
    }
    for key in order:
        ops[key]()


def test_exposition_deterministic_across_insertion_order():
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    _populate(r1, ["a", "b", "c", "d"])
    _populate(r2, ["d", "c", "b", "a"])
    text = r1.exposition()
    assert text == r2.exposition()
    assert text.endswith("\n")
    # Sorted families, sorted children within.
    assert text.index("fed_buffer_fill_ratio") < text.index("fed_updates_total")
    assert text.index('result="accepted"') < text.index('result="rejected_stale"')


def test_label_escaping_round_trips():
    reg = MetricsRegistry()
    weird = 'he said "hi"\\\n'
    reg.counter("x_total", labels=("msg",)).labels(msg=weird).inc()
    parsed = parse_prometheus_text(reg.exposition())
    assert sample_value(parsed, "x_total", {"msg": weird}) == 1


def test_help_escaping_round_trips():
    """A literal backslash followed by 'n' in HELP text must survive the
    escape→parse round trip (sequential str.replace would mis-decode it)."""
    reg = MetricsRegistry()
    tricky = "path\\nfoo and a real\nnewline"
    reg.counter("y_total", help=tricky).inc()
    parsed = parse_prometheus_text(reg.exposition())
    assert parsed["y_total"]["help"] == tricky


# ---- the HTTP loop ----


def test_http_scrape_round_trips_every_sample():
    reg = MetricsRegistry()
    _populate(reg, ["a", "b", "c", "d"])
    with MetricsExporter(reg) as exporter:
        req = urllib.request.urlopen(exporter.url, timeout=5)
        assert req.headers["Content-Type"] == CONTENT_TYPE
        body = req.read().decode("utf-8")
        assert body == reg.exposition()
        parsed = scrape(exporter.url)
        # liveness + 404 routes: /healthz answers a JSON body (round 16)
        # so "up" and "warm" are distinguishable.
        health = urllib.request.urlopen(
            exporter.url.replace("/metrics", "/healthz"), timeout=5
        )
        assert health.headers["Content-Type"].startswith("application/json")
        body = json.loads(health.read())
        assert body["status"] == "ok"
        assert body["families"] == 3  # the three families _populate built
        assert body["uptime_seconds"] >= 0
        assert isinstance(body["spans_installed"], bool)
        assert "git" in body  # a string in a checkout, null in a wheel
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                exporter.url.replace("/metrics", "/nope"), timeout=5
            )
    assert sample_value(
        parsed, "fed_updates_total", {"result": "accepted"}
    ) == 7
    assert sample_value(parsed, "fed_buffer_fill_ratio") == 0.5
    assert parsed["serve_request_seconds"]["type"] == "histogram"
    assert sample_value(
        parsed, "serve_request_seconds", {"__sample__": "_count"}
    ) == 3
    assert sample_value(
        parsed, "serve_request_seconds", {"__sample__": "_bucket", "le": "+Inf"}
    ) == 3
    assert sample_value(
        parsed, "serve_request_seconds", {"__sample__": "_bucket", "le": "0.1"}
    ) == 1
    # Concurrent updates during scrapes never tear the text format.
    reg.counter("fed_updates_total", labels=("result",)).labels(
        result="accepted"
    ).inc()
    parse_prometheus_text(reg.exposition())


def test_parser_rejects_garbage_loudly():
    with pytest.raises(ValueError, match="unparseable"):
        parse_prometheus_text("fed_updates_total one\n")
    with pytest.raises(ValueError, match="unparseable"):
        parse_prometheus_text('x_total{result=unquoted} 1\n')


# ---- StreamingPercentiles.merge (satellite) ----


def test_merge_exact_pooled_percentiles_under_capacity():
    """Property: across seeds and split points, while the pooled sample
    fits capacity the merged percentiles EQUAL numpy over the pool."""
    for seed in range(5):
        rng = np.random.default_rng(seed)
        samples = rng.exponential(10.0, size=200)
        cut = int(rng.integers(1, 199))
        a = StreamingPercentiles(capacity=512, seed=seed)
        b = StreamingPercentiles(capacity=512, seed=seed + 100)
        for v in samples[:cut]:
            a.add(v)
        for v in samples[cut:]:
            b.add(v)
        a.merge(b)
        assert a.count == 200
        for q in (50, 90, 95, 99):
            assert a.percentile(q) == pytest.approx(
                float(np.percentile(samples, q)), rel=1e-12
            ), (seed, cut, q)


def test_merge_past_capacity_exact_moments_sane_percentiles():
    rng = np.random.default_rng(7)
    sa = rng.normal(100.0, 5.0, size=4000)
    sb = rng.normal(200.0, 5.0, size=6000)
    a = StreamingPercentiles(capacity=1024, seed=1)
    b = StreamingPercentiles(capacity=1024, seed=2)
    for v in sa:
        a.add(v)
    for v in sb:
        b.add(v)
    a.merge(b)
    pooled = np.concatenate([sa, sb])
    # count/sum/min/max merge EXACTLY whatever the reservoir sampled.
    assert a.count == 10000
    s = a.summary()
    assert s["max"] == pytest.approx(float(pooled.max()))
    assert s["min"] == pytest.approx(float(pooled.min()))
    # The median of a 40/60 bimodal pool sits in the upper mode; the
    # weighted sample must reflect each side's stream share.
    assert abs(a.percentile(50) - float(np.percentile(pooled, 50))) < 15.0
    assert abs(a.percentile(95) - float(np.percentile(pooled, 95))) < 5.0


def test_merge_deterministic_and_self_merge_refused():
    def build():
        a = StreamingPercentiles(capacity=64, seed=3)
        b = StreamingPercentiles(capacity=64, seed=4)
        for i in range(300):
            a.add(float(i))
            b.add(float(1000 + i))
        a.merge(b)
        return a

    r1, r2 = build(), build()
    assert r1._values == r2._values  # order-pinned, seeded: bit-identical
    assert r1.count == r2.count == 600
    with pytest.raises(ValueError, match="double-count"):
        r1.merge(r1)
    # Merging an empty reservoir is the identity.
    before = list(r1._values)
    r1.merge(StreamingPercentiles(capacity=64, seed=9))
    assert r1._values == before and r1.count == 600


# ---- spans ----


def test_spans_correlate_and_record_monotonic_durations(tmp_path):
    path = tmp_path / "spans.jsonl"
    tracing.install(path)
    try:
        with tracing.span("fed.flush", trace="round-3", version=4) as h:
            with tracing.span(
                "client.push", trace="round-3", parent=h.span_id, cname="c0"
            ) as child:
                child.set(upload_bytes=123)
    finally:
        tracing.uninstall()
    records = tracing.read_spans(path)
    assert [r["name"] for r in records] == ["client.push", "fed.flush"]
    push, flush = records
    assert push["trace"] == flush["trace"] == "round-3"
    assert push["parent"] == flush["span"]
    assert push["upload_bytes"] == 123 and flush["version"] == 4
    assert 0 <= push["dur_s"] <= flush["dur_s"]
    assert flush["t"] <= push["t"]  # outer started first
    # Every line is strict JSON (the CI artifact is jq-safe).
    for line in path.read_text().splitlines():
        json.loads(line)
    assert tracing.current() is None
    with tracing.span("serve.batch", trace="bucket-16") as h:
        assert h is None  # uninstalled -> no-op, sites never branch


def test_span_recorder_rotation_never_tears_a_line(tmp_path):
    """Satellite (round 16): size-based rotation bounds an hours-long
    soak's JSONL; every file in the rotated set holds only whole JSON
    lines, at most keep+1 files exist, and the record stream survives."""
    path = tmp_path / "spans.jsonl"
    with tracing.SpanRecorder(path, max_bytes=1500, keep=2) as rec:
        for i in range(60):
            with rec.span("w.x", trace=f"t-{i}", payload="p" * 64):
                pass
    files = tracing.span_files(path)
    assert str(path) in files
    assert 2 <= len(files) <= 3  # rotated at least once, keep=2 honored
    assert not (tmp_path / "spans.jsonl.3").exists()
    total = 0
    for f in files:
        text = open(f, encoding="utf-8").read()
        assert text.endswith("\n")  # no torn tail
        for line in text.splitlines():
            rec_obj = json.loads(line)  # every line strict JSON
            assert rec_obj["name"] == "w.x"
            total += 1
        import os as _os

        assert _os.path.getsize(f) <= 1500 + 200  # one-line slack
    assert 0 < total <= 60  # keep=2 may have dropped the oldest lines
    # span_files orders oldest → newest: the newest record is in the last.
    last = tracing.read_spans(files[-1])
    assert last[-1]["trace"] == "t-59"


def test_trace_context_wire_round_trip_and_degradation():
    ctx = tracing.TraceContext("fedtr-v7", "push:c0:r3")
    assert tracing.TraceContext.from_wire(ctx.to_wire()) == ctx
    assert tracing.version_trace(7) == "fedtr-v7"
    assert tracing.flush_context(8) == tracing.TraceContext(
        "fedtr-v7", "flush:v8"
    )
    # The dropped-context contract: anything malformed parses to None.
    for garbage in (None, 7, b"x#y", "", "nohash", "#", "a#", "#b", "x" * 500):
        assert tracing.TraceContext.from_wire(garbage) is None


def test_span_recorder_thread_safe(tmp_path):
    path = tmp_path / "spans.jsonl"
    with tracing.SpanRecorder(path) as rec:
        def worker(i):
            for j in range(20):
                with rec.span("w", trace=f"t-{i}", j=j):
                    pass

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    records = tracing.read_spans(path)
    assert len(records) == 80
    assert len({r["span"] for r in records}) == 80  # ids unique


# ---- leak sentries ----


def test_leak_sentry_steady_and_tripping(monkeypatch):
    reg = MetricsRegistry()
    fake = {"rss": 1000}
    monkeypatch.setattr(sentries, "rss_bytes", lambda: fake["rss"])
    monkeypatch.setattr(sentries, "device_memory_bytes", lambda: 0)
    sentry = sentries.LeakSentry(registry=reg, rss_slack_bytes=500)
    sentry.mark()
    fake["rss"] = 1400  # inside slack
    sentry.assert_steady()
    assert sentry.steady()
    fake["rss"] = 1600  # past slack: a leak
    with pytest.raises(sentries.LeakError, match="RSS grew 600"):
        sentry.assert_steady()
    # Gauges ride the scrape: collect-time callbacks see the last sample.
    parsed = parse_prometheus_text(reg.exposition())
    assert sample_value(parsed, "process_resident_bytes") == 1600
    assert sample_value(parsed, "process_resident_watermark_bytes") == 1600
    summary = sentry.summary()
    assert summary["steady"] is False and summary["deltas"]["rss"] == 600


def test_leak_sentry_real_process_watermarks():
    sentry = sentries.LeakSentry(registry=MetricsRegistry())
    reading = sentry.sample()
    assert reading["rss"] > 0  # a real process is resident
    assert sentry.watermarks()["rss"] >= reading["rss"] > 0
    with pytest.raises(RuntimeError, match="before mark"):
        sentries.LeakSentry(registry=MetricsRegistry()).deltas()


# ---- flight recorder (round 16) ----


def test_flight_ring_bounded_and_spans_feed_it(tmp_path):
    from fedcrack_tpu.obs import flight

    ring = flight.install(path=str(tmp_path / "flight.json"), capacity=8)
    try:
        for i in range(20):
            flight.note("x", i=i)
        events = ring.snapshot()
        assert len(events) == 8  # bounded ring: only the last 8 survive
        assert [e["i"] for e in events] == list(range(12, 20))
        assert ring._seen == 20
        # Spans feed the ring for FREE even with NO span recorder installed.
        assert tracing.current() is None
        with tracing.span("fed.flush", trace="fedtr-v1", ctx="fedtr-v1#flush:v2"):
            pass
        last = ring.snapshot()[-1]
        assert last["kind"] == "span" and last["name"] == "fed.flush"
        assert last["ctx"] == "fedtr-v1#flush:v2" and last["dur_s"] >= 0
        path = flight.dump("unit test")
        payload = json.loads(open(path).read())
        assert payload["reason"] == "unit test"
        assert payload["events_seen"] == 21
        assert payload["events"][-1]["kind"] == "span"
        assert "metrics_exposition" in payload
    finally:
        flight.uninstall()
    assert flight.current() is None
    flight.note("after", x=1)  # uninstalled: a no-op, never an error
    assert flight.dump("after") is None


@pytest.mark.filterwarnings("ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_flight_dump_on_thread_crash_and_sigusr2(tmp_path):
    """The dump triggers: an unhandled exception in a thread and SIGUSR2
    both write the ring to disk (the excepthooks are chained, so default
    reporting still happens)."""
    import os
    import signal
    import time as _time

    from fedcrack_tpu.obs import flight

    path = str(tmp_path / "flight.json")
    flight.install(path=path, capacity=64)
    try:
        flight.note("before_crash", detail="context the post-mortem needs")

        def boom():
            raise RuntimeError("injected thread death")

        t = threading.Thread(target=boom, name="doomed")
        t.start()
        t.join()
        payload = json.loads(open(path).read())
        assert "injected thread death" in payload["reason"]
        assert any(e["kind"] == "before_crash" for e in payload["events"])
        if hasattr(signal, "SIGUSR2"):
            os.remove(path)
            os.kill(os.getpid(), signal.SIGUSR2)
            for _ in range(100):  # delivery is asynchronous-ish; bounded wait
                if os.path.exists(path):
                    break
                _time.sleep(0.01)
            payload = json.loads(open(path).read())
            assert payload["reason"] == "SIGUSR2"
    finally:
        flight.uninstall()


# ---- SLO watchdog (round 16) ----


def _watchdog_registry():
    reg = MetricsRegistry()
    reg.counter("fed_updates_total", "u", labels=("result",)).labels(
        result="accepted"
    ).inc(10)
    h = reg.histogram("serve_request_seconds", "lat", buckets=(0.1, 1.0, 10.0))
    for v in [0.05] * 90 + [0.5] * 9 + [5.0]:
        h.observe(v)
    reg.gauge("serve_recompiles_total", "r").set(0)
    return reg


def test_watchdog_stats_value_quantile_and_rate(monkeypatch):
    from fedcrack_tpu.obs import watchdog as wdm

    reg = _watchdog_registry()
    rules = [
        wdm.SloRule(name="v", metric="fed_updates_total",
                    labels={"result": "accepted"}, op=">=", threshold=10),
        wdm.SloRule(name="p95", metric="serve_request_seconds", stat="p95",
                    op="<=", threshold=1.0),
        wdm.SloRule(name="p50", metric="serve_request_seconds", stat="p50",
                    op="<=", threshold=0.1),
        wdm.SloRule(name="n", metric="serve_request_seconds", stat="count",
                    op="==", threshold=100),
        wdm.SloRule(name="rate", metric="fed_updates_total",
                    labels={"result": "accepted"}, stat="rate", op=">=",
                    threshold=1.0, min_elapsed_s=0.01),
        wdm.SloRule(name="absent", metric="no_such_total", op="==", threshold=0),
    ]
    wd = wdm.Watchdog(rules, registry=reg)
    r1 = {r["rule"]: r for r in wd.evaluate()["results"]}
    assert r1["v"]["value"] == 10 and r1["v"]["ok"]
    # p95 sits in the (0.1, 1.0] bucket: 90 of 100 below 0.1, 99 below 1.0.
    assert 0.1 < r1["p95"]["value"] <= 1.0 and r1["p95"]["ok"]
    assert r1["p50"]["value"] <= 0.1 and r1["p50"]["ok"]
    assert r1["n"]["value"] == 100
    assert r1["rate"]["value"] is None  # first evaluation: no window yet
    assert r1["absent"]["value"] is None and r1["absent"]["breach"] is False
    import time as _time

    _time.sleep(0.02)
    reg.counter("fed_updates_total", labels=("result",)).labels(
        result="accepted"
    ).inc(5)
    r2 = {r["rule"]: r for r in wd.evaluate()["results"]}
    assert r2["rate"]["value"] > 0 and r2["rate"]["ok"]
    audit = wd.audit()
    assert audit["breaches"] == [] and audit["evaluations"] == 2
    assert audit["never_determinate"] == ["absent"]
    assert not audit["all_rules_evaluated"] and not audit["clean"]


def test_watchdog_consecutive_rides_out_blips():
    """The `for:`-style clause: consecutive=3 means two failing
    evaluations with a recovery between them never breach; three in a row
    do. A bursty plane (storm gust, kill→restart window) must not page."""
    from fedcrack_tpu.obs import watchdog as wdm

    reg = MetricsRegistry()
    g = reg.gauge("fed_buffer_fill_ratio", "fill")
    rule = wdm.SloRule(
        name="floor", metric="fed_buffer_fill_ratio", op=">=",
        threshold=1.0, consecutive=3,
    )
    wd = wdm.Watchdog([rule], registry=reg)

    def one(value):
        g.set(value)
        return wd.evaluate()["breaches"]

    assert one(0.0) == []          # fail #1
    assert one(0.0) == []          # fail #2
    assert one(2.0) == []          # recovery resets the streak
    assert one(0.0) == []          # fail #1 again
    assert one(0.0) == []          # fail #2
    assert one(0.0) != []          # fail #3: SUSTAINED -> breach
    audit = wd.audit()
    assert len(audit["breaches"]) == 1 and not audit["clean"]
    with pytest.raises(ValueError, match="consecutive"):
        wdm.SloRule(name="x", metric="y_total", op="==", threshold=0,
                    consecutive=0)


def test_watchdog_breach_dumps_flight_and_audits_dirty(tmp_path):
    from fedcrack_tpu.obs import flight
    from fedcrack_tpu.obs import watchdog as wdm

    reg = _watchdog_registry()
    rules = [
        wdm.SloRule(name="impossible", metric="fed_updates_total",
                    labels={"result": "accepted"}, op=">=", threshold=1e12,
                    on_missing="breach"),
    ]
    path = str(tmp_path / "flight.json")
    flight.install(path=path)
    try:
        wd = wdm.Watchdog(rules, registry=reg)
        report = wd.enforce()
        assert report["breaches"][0]["rule"] == "impossible"
        payload = json.loads(open(path).read())
        assert payload["reason"] == "watchdog breach: impossible"
        # Watchdog samples themselves feed the ring (metric-sample deltas).
        assert any(e["kind"] == "watchdog.eval" for e in payload["events"])
        wd.enforce()  # a second breach does not re-dump (once per watchdog)
        audit = wd.audit()
        assert not audit["clean"] and len(audit["breaches"]) == 2
    finally:
        flight.uninstall()
    assert wdm.BREACH_EXIT != 0


def test_watchdog_rule_files_parse_and_default_config_matches():
    """configs/slo_default.json must stay the mirror of the built-in rule
    set; malformed rule files fail loudly."""
    import os

    from fedcrack_tpu.obs import watchdog as wdm

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    loaded = wdm.load_rules(os.path.join(root, "configs", "slo_default.json"))
    assert loaded == wdm.default_rules()
    smoke = wdm.load_rules(os.path.join(root, "configs", "slo_breach_smoke.json"))
    assert smoke[0].on_missing == "breach" and smoke[0].threshold >= 1e12
    with pytest.raises(ValueError, match="unknown op"):
        wdm.SloRule(name="x", metric="y_total", op="~", threshold=1)
    with pytest.raises(ValueError, match="unknown stat"):
        wdm.SloRule(name="x", metric="y_total", op="<=", threshold=1, stat="p42")
    with pytest.raises(ValueError, match="duplicate"):
        wdm.Watchdog([wdm.SloRule(name="a", metric="x_total", op="==", threshold=0)] * 2)


# ---- the concurrent mini-soak ----


def _assert_soak_clean(artifact: dict):
    audit = artifact["audit"]
    assert audit["clean"], json.dumps(
        {"audit": audit, "watchdog": artifact["watchdog"]},
        indent=1, sort_keys=True,
    )
    assert audit["zero_torn_versions"] and audit["torn_versions"] == 0
    assert audit["serve_healthy"]
    assert audit["ef_mass_conserved"]
    assert audit["statefile_restore_bit_identical"]
    assert audit["watermarks_steady"]
    assert audit["recompiles_since_warmup"] == 0
    scrape_block = artifact["scrape"]
    assert scrape_block["all_planes_covered"], scrape_block["planes_covered"]
    assert scrape_block["mid_soak_families"] > 0  # scraped LIVE, mid-run
    assert artifact["serve"]["completed"] > 0
    assert artifact["serve"]["failed"] == 0
    assert artifact["federation"]["flushes"] > 0
    assert artifact["spans"]["total"] > 0
    for name in ("serve.batch", "fed.flush", "driver.round", "client.train"):
        assert artifact["spans"]["by_name"].get(name, 0) > 0, name
    # Round 16: the machine-checked SLO audit and the stitched trace.
    wd = artifact["watchdog"]
    assert wd["clean"] and wd["all_rules_evaluated"], wd
    assert wd["breaches"] == [] and wd["evaluations"] > 1
    assert audit["watchdog_clean"]
    tr = artifact["tracing"]
    assert tr["complete"], tr
    # One trace id crossed the client → root → serve planes.
    assert {"client", "fed", "serve"} <= set(tr["planes_crossed"])
    assert tr["trace"].startswith("fedtr-v")
    for stage in ("fed.flush", "serve.swap", "serve.batch"):
        assert stage in tr["stages"], (stage, tr)
    # Upstream reached the flush via a direct push or an edge partial
    # (the best chain may be either — both are client-plane-rooted).
    assert {"client.push", "edge.flush_partial"} & set(tr["stages"]), tr


def test_mini_soak_short_wall_clean_audit():
    """Tier-1: every plane concurrently for a few seconds — buffered
    federation, edge shard, serve + live hot-swap off the federation's
    statefile, driver leg, chaos rolling, a mid-soak server kill→restart —
    self-scraped over real HTTP and closed with a clean invariant audit."""
    from fedcrack_tpu.tools.soak import run_soak

    artifact = run_soak(duration_s=3.0, seed=0)
    _assert_soak_clean(artifact)
    assert artifact["federation"]["kill_restart"]["killed"]
    assert artifact["serve"]["swaps"] > 0  # training reached serving, live


@pytest.mark.slow
def test_mini_soak_sixty_seconds():
    """The ROADMAP's soak shrunk to a minute: long enough for hundreds of
    flushes and dozens of swaps; the same audit must stay clean."""
    from fedcrack_tpu.tools.soak import run_soak

    artifact = run_soak(duration_s=60.0, seed=0)
    _assert_soak_clean(artifact)
    assert artifact["federation"]["kill_restart"]["killed"]
    assert artifact["serve"]["swaps"] >= 5
    assert artifact["federation"]["global_versions"] >= 20
