"""Crack quantifier: closed-form shapes and the predict flow."""

import numpy as np
import pytest

from fedcrack_tpu.tools import quantify_mask
from fedcrack_tpu.tools.quantify import annotate


def test_single_square_crack():
    mask = np.zeros((64, 64), np.uint8)
    mask[20:40, 20:40] = 255  # 20x20 square
    s = quantify_mask(mask)
    assert s.contour_count == 1
    # cv2 contour area of a filled 20x20 block is (19)^2 (contour runs on
    # pixel centers); perimeter ~ 4*19
    assert abs(s.total_area_px - 361) < 2
    assert abs(s.total_perimeter_px - 76) < 2
    c = s.contours[0]
    assert c.approx_points_10pct == 4  # a square simplifies to 4 vertices
    assert abs(s.crack_fraction - 400 / 4096) < 1e-6


def test_empty_mask():
    s = quantify_mask(np.zeros((32, 32), np.uint8))
    assert s.contour_count == 0 and s.total_area_px == 0


def test_float01_mask_accepted():
    mask = np.zeros((32, 32), np.float32)
    mask[8:16, 8:24] = 1.0
    s = quantify_mask(mask)
    assert s.contour_count == 1


def test_two_separate_cracks():
    mask = np.zeros((64, 64), np.uint8)
    mask[5:15, 5:15] = 255
    mask[40:60, 40:50] = 255
    s = quantify_mask(mask)
    assert s.contour_count == 2


def test_annotate_returns_uint8_rgb():
    img = np.random.default_rng(0).uniform(size=(32, 32, 3)).astype(np.float32)
    mask = np.zeros((32, 32), np.uint8)
    mask[10:20, 10:20] = 255
    out = annotate(img, mask)
    assert out.dtype == np.uint8 and out.shape == (32, 32, 3)
    assert (out != (np.clip(img, 0, 1) * 255).astype(np.uint8)).any()


# Tier-1 budget re-balance (round 14): a full predict+quantify tool smoke
# (~15 s of model compiles); quantify's contour math stays tier-1 in this
# module's unit tests and the predict program in test_serve/test_model.
@pytest.mark.slow
def test_predict_and_quantify_writes_outputs(tmp_path):
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.pipeline import ArrayDataset
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.tools.quantify import predict_and_quantify
    from fedcrack_tpu.train import create_train_state

    state = create_train_state(jax.random.key(0), ModelConfig(img_size=32))
    images, masks = synth_crack_batch(4, 32, seed=0)
    ds = ArrayDataset(images, masks, batch_size=2, shuffle=False)
    reports = predict_and_quantify(state, ds, out_dir=str(tmp_path), max_images=3)
    assert len(reports) == 3
    assert (tmp_path / "pred_000.png").exists()
    assert (tmp_path / "overlay_002.png").exists()
    assert all("area_px" in r for r in reports)


def _write_mask_pngs(out_dir, specs):
    """specs: {name: (size, fill_box or None)} -> PNG masks on disk."""
    import os

    import cv2

    os.makedirs(out_dir, exist_ok=True)
    for name, (size, box) in specs.items():
        mask = np.zeros((size, size), np.uint8)
        if box is not None:
            y0, y1, x0, x1 = box
            mask[y0:y1, x0:x1] = 255
        cv2.imwrite(str(out_dir / name), mask)


def test_quantify_mask_dir_batch_stats(tmp_path):
    """Round-10 batch mode: a directory of predicted masks (what the serving
    plane emits via load_gen --out-dir) quantified WITHOUT a model, with
    per-image records in stable sorted order plus aggregate totals."""
    from fedcrack_tpu.tools.quantify import quantify_mask_dir

    _write_mask_pngs(
        tmp_path,
        {
            "mask_00002.png": (64, (20, 40, 20, 40)),  # one 20x20 crack
            "mask_00000.png": (64, None),              # empty
            "mask_00001.png": (64, (5, 15, 5, 15)),    # one 10x10 crack
        },
    )
    (tmp_path / "notes.txt").write_text("not a mask")  # ignored (not an image)
    report = quantify_mask_dir(str(tmp_path))
    names = [r["image"] for r in report["images"]]
    assert names == ["mask_00000.png", "mask_00001.png", "mask_00002.png"]
    assert report["images"][0]["contours"] == 0
    assert report["images"][1]["contours"] == 1
    assert report["totals"]["images"] == 3
    assert report["totals"]["contours"] == 2
    assert report["totals"]["area_px"] == pytest.approx(
        sum(r["area_px"] for r in report["images"])
    )
    assert report["totals"]["mean_crack_fraction"] == pytest.approx(
        np.mean([r["crack_fraction"] for r in report["images"]])
    )
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no mask images"):
        quantify_mask_dir(str(empty))
    with pytest.raises(ValueError, match="not a directory"):
        quantify_mask_dir(str(tmp_path / "does_not_exist"))


def test_quantify_cli_pred_dir_out_json(tmp_path, capsys):
    """The CLI contract the serving pipeline uses: --pred-dir needs no
    --weights, prints one JSON line per image + a totals line, and --out-json
    writes the machine-readable report."""
    import json

    from fedcrack_tpu.tools.quantify import main as quantify_main

    pred = tmp_path / "pred"
    _write_mask_pngs(pred, {"a.png": (32, (8, 16, 8, 24)), "b.png": (32, None)})
    out_json = tmp_path / "stats.json"
    quantify_main(
        ["--pred-dir", str(pred), "--out-json", str(out_json)]
    )
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 3  # 2 per-image lines + totals
    per_image = [json.loads(line) for line in lines[:2]]
    assert [r["image"] for r in per_image] == ["a.png", "b.png"]
    totals = json.loads(lines[-1])["totals"]
    assert totals["images"] == 2 and totals["contours"] == 1
    with open(out_json) as f:
        on_disk = json.load(f)
    assert on_disk["totals"] == totals
    assert [r["image"] for r in on_disk["images"]] == ["a.png", "b.png"]


def test_quantify_cli_weights_still_required_without_pred_dir(capsys):
    from fedcrack_tpu.tools.quantify import main as quantify_main

    with pytest.raises(SystemExit):
        quantify_main(["--synthetic", "2"])
    assert "--weights is required" in capsys.readouterr().err


@pytest.mark.slow
def test_refscale_federation_tool_smoke(tmp_path):
    """The reference-complete federation driver (tools/refscale_federation)
    at toy scale: artifact schema, N-client serial fits with non-degenerate
    FedAvg, per-round eval records, and the staging overlap wiring all
    exercised — the real run (bench_runs/r05_refscale_federation.json) is
    this at 2 clients x 5 rounds x 10 epochs x 388 steps."""
    import json

    from fedcrack_tpu.tools.refscale_federation import main

    out = tmp_path / "refscale.json"
    rc = main(
        [
            "--clients", "2", "--rounds", "2", "--epochs", "1",
            "--samples", "32", "--batch", "4",
            "--img", "32", "--eval-samples", "8", "--dtype", "float32",
            "--out", str(out),
        ]
    )
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["workload"]["rounds"] == 2
    assert art["workload"]["clients"] == 2
    assert len(art["rounds"]) == 2
    for r in art["rounds"]:
        assert len(r["fits"]) == 2
        for f in r["fits"]:
            assert f["staged_bytes"] > 0
        assert "iou" in r["eval"] and "loss" in r["eval"]
        # Non-degenerate aggregation: both clients moved, and they moved to
        # DIFFERENT weights (distinct shards diverge under local SGD).
        assert len(r["update_l2"]) == 2 and all(u > 0 for u in r["update_l2"])
        assert len(r["client_divergence_l2"]) == 1
        assert r["client_divergence_l2"][0] > 0
    # The very last fit of the schedule has nothing left to stage ahead.
    assert art["rounds"][-1]["fits"][-1]["overlapped_next_fit_staging"] is False
    assert art["rounds"][0]["fits"][0]["overlapped_next_fit_staging"] is True
    assert len(art["summary"]["eval_iou_trajectory"]) == 2
    # Round 9: the held-out eval slab is device-resident — the one-time
    # transfer is charged to the first round's eval_stage_s, 0.0 after.
    assert art["rounds"][0]["eval_stage_s"] > 0.0
    assert all(r["eval_stage_s"] == 0.0 for r in art["rounds"][1:])
    assert art["summary"]["eval_staged_bytes"] > 0
    assert art["workload"]["data_placement"] == "streamed"


@pytest.mark.slow
def test_ab_pallas_bce_harness_smoke(tmp_path):
    """The BCE-kernel A/B harness (tools/ab_pallas_bce) at toy scale:
    artifact schema + slope-fit wiring, single impl — the Pallas INTERPRETER
    cannot run inside the shard_map round program on CPU (jax
    hlo_interpreter vma limitation), and the compiled kernel needs a real
    TPU, so the two-impl comparison is exercised only by the TPU artifact
    (bench_runs/r05_pallas_bce_ab.json). Kernel-vs-XLA numerics parity is
    test_pallas_bce's job. Slow-marked (round-12 tier-1 budget re-balance,
    the r4/r9 precedent): ~80-95 s of tools-level compiles whose numeric
    semantics stay tier-1 via test_pallas_bce and whose artifact schema is
    retroactively validated by test_bench over bench_runs/."""
    import json

    from fedcrack_tpu.tools.ab_pallas_bce import main

    out = tmp_path / "ab.json"
    rc = main(
        [
            "--sizes", "32", "--steps", "2", "--batch", "2", "--reps", "1",
            "--fit-factor", "2", "--impls", "jnp",
            "--dtype", "float32", "--out", str(out),
        ]
    )
    assert rc == 0
    art = json.loads(out.read_text())
    point = art["points"]["float32_32"]
    # ADVICE r5 #3: per-impl dicts live under "impls"; derived scalars are
    # sibling keys — impl iteration needs no non-dict special case.
    pts = point["impls"]
    assert all(isinstance(v, dict) for v in pts.values())
    assert pts["jnp"]["round_s_short"] > 0
    assert pts["jnp"]["round_s_long"] > 0
    # per_step_ms may be None if CPU timing noise defeats the 2-point fit at
    # this toy scale; the schema must carry the key either way.
    assert "per_step_ms" in pts["jnp"]
    # env must be restored (other tests rely on auto-dispatch)
    import os

    assert os.environ.get("FEDCRACK_BCE_IMPL") is None


@pytest.mark.slow
def test_profile_step_tool_smoke(tmp_path):
    """tools/profile_step at toy scale: trace capture + xprof hlo_stats
    aggregation (the machinery behind the 256 px north-star profile).
    Slow-marked (round-12 tier-1 budget re-balance, the r4/r9 precedent):
    a tools-level smoke of display/profiling machinery — no protocol
    semantics ride on it, and it still runs in the slow suite."""
    import json

    from fedcrack_tpu.tools.profile_step import main

    out = tmp_path / "prof.json"
    rc = main(
        [
            "--img", "32", "--steps", "2", "--batch", "2", "--rounds", "1",
            "--dtype", "float32", "--out", str(out),
        ]
    )
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["measured"]["round_wall_s_median"] > 0
    assert art["xplane_files"], "profiler produced no xplane capture"
    if art["hlo_stats"] is not None:
        cats = art["hlo_stats"]["by_category"]
        assert cats and abs(sum(c["fraction"] for c in cats.values()) - 1.0) < 0.02
        assert art["hlo_stats"]["top_ops"]


@pytest.mark.slow
def test_refscale_federation_resident_placement_matches_streamed():
    """--data-placement resident (session-resident client pools + per-fit
    index uploads) reproduces the streamed run's eval trajectory exactly —
    both placements consume one rng permutation per fit — while shipping
    only kilobytes per fit after the one-time pool staging."""
    import argparse

    from fedcrack_tpu.tools.refscale_federation import run_refscale_federation

    def mk(placement):
        return argparse.Namespace(
            clients=2, rounds=2, epochs=2, samples=16, batch=4, img=32,
            dtype="float32", eval_samples=8, pos_weight=2.0, lr=1e-3, seed=0,
            segments=0, server_optimizer="fedavg", server_lr=1.0,
            server_momentum=0.9, ckpt_dir="", resume=False,
            data_placement=placement,
        )

    streamed = run_refscale_federation(mk("streamed"))
    resident = run_refscale_federation(mk("resident"))
    assert resident["workload"]["data_placement"] == "resident"
    assert [r["eval"] for r in resident["rounds"]] == [
        r["eval"] for r in streamed["rounds"]
    ]
    slab = streamed["rounds"][0]["fits"][0]["staged_bytes"]
    assert resident["summary"]["pool_bytes_total"] > 0
    for r in resident["rounds"]:
        for f in r["fits"]:
            assert 0 < f["staged_bytes"] * 20 <= slab  # indices only
    assert streamed["summary"]["pool_bytes_total"] is None


# Slow-marked (round 9): three full tool runs with fresh 32 px compiles cost
# ~155 s — the single largest tier-1 line item — and the kill->resume
# semantics stay pinned tier-1 at the driver level
# (test_segmented.py::test_driver_checkpoint_kill_and_resume) plus the
# statefile tests in test_ckpt.py; this tool-level twin is belt-and-
# suspenders coverage the slow suite keeps (same budget policy as
# test_segmented's K in {1,2}).
@pytest.mark.slow
def test_refscale_federation_kill_and_resume(tmp_path):
    """Round 7 (VERDICT r5 #7): the tool checkpointed after every round
    resumes a killed session at round r+1 with an identical trajectory —
    per-round evals equal to the uninterrupted run — including the FedOpt
    server-optimizer moments and the per-client shuffle rng state."""
    import argparse

    from fedcrack_tpu.tools.refscale_federation import run_refscale_federation

    def mk(rounds, **kw):
        base = dict(
            clients=2, rounds=rounds, epochs=2, samples=16, batch=4, img=32,
            dtype="float32", eval_samples=8, pos_weight=2.0, lr=1e-3, seed=0,
            segments=0, server_optimizer="fedavgm", server_lr=1.0,
            server_momentum=0.9, ckpt_dir="", resume=False,
        )
        base.update(kw)
        return argparse.Namespace(**base)

    straight = run_refscale_federation(mk(3))
    # "Kill" after round 2 of 3: a 2-round run leaves the checkpoint a
    # 3-round run would have left at that boundary...
    run_refscale_federation(mk(2, ckpt_dir=str(tmp_path / "ck")))
    # ...and the resumed process finishes round 3 on the same trajectory.
    resumed = run_refscale_federation(
        mk(3, ckpt_dir=str(tmp_path / "ck"), resume=True)
    )
    assert resumed["resumed_from"] == 2
    assert straight["resumed_from"] == 0
    assert [r["eval"] for r in resumed["rounds"]] == [
        r["eval"] for r in straight["rounds"]
    ]
    assert resumed["workload"]["server_optimizer"] == "fedavgm"
    assert "segments" in resumed["workload"]
