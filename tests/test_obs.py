"""Observability: JSONL metrics sink, round records, profiler hook.

The reference's observability is print banners + a disabled TensorBoard
upload path (SURVEY.md §5.1/§5.5); these tests pin the structured
replacement.
"""

import dataclasses
import json
import threading

import jax.numpy as jnp
import numpy as np

from fedcrack_tpu.configs import FedConfig, ModelConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_to_bytes
from fedcrack_tpu.obs import MetricsLogger, profiler_trace, read_metrics, stopwatch

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)


def test_metrics_logger_round_trip(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as m:
        m.log("round", round=1, loss=0.5, clients=["a", "b"])
        m.log("fit", loss=jnp.float32(0.25), n=np.int64(3))
    records = read_metrics(path)
    assert [r["kind"] for r in records] == ["round", "fit"]
    assert records[0]["clients"] == ["a", "b"]
    # jax/numpy scalars come back as plain JSON numbers, integers as ints
    assert records[1]["loss"] == 0.25
    assert records[1]["n"] == 3
    assert isinstance(records[1]["n"], int)
    assert all("t" in r and "ts" in r for r in records)


def test_metrics_logger_kind_filter_and_append(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as m:
        m.log("a", x=1)
    with MetricsLogger(path) as m:  # append, not truncate
        m.log("b", x=2)
    assert len(read_metrics(path)) == 2
    assert [r["x"] for r in read_metrics(path, kind="b")] == [2]


def test_metrics_logger_thread_safety(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as m:
        threads = [
            threading.Thread(target=lambda i=i: [m.log("t", i=i) for _ in range(50)])
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    records = read_metrics(path)
    assert len(records) == 200
    # every line parsed cleanly (no interleaved writes)
    for rec in records:
        assert rec["kind"] == "t"


def test_stopwatch_measures_time():
    with stopwatch() as w:
        pass
    assert 0.0 <= w["seconds"] < 1.0


def test_profiler_trace_disabled_is_noop():
    with profiler_trace(None):
        x = jnp.ones((4,)) + 1
    assert float(x.sum()) == 8.0


def test_profiler_trace_writes_events(tmp_path):
    logdir = tmp_path / "trace"
    with profiler_trace(str(logdir)):
        jnp.ones((8, 8)).sum().block_until_ready()
    produced = list(logdir.rglob("*"))
    assert produced, "profiler trace produced no files"


def test_round_history_carries_wall_clock_and_bytes():
    """The state machine's history entries now carry the observability
    fields (wall_clock_s, bytes_received, bytes_broadcast)."""
    from fedcrack_tpu.train.local import create_train_state

    import jax

    cfg = FedConfig(
        max_rounds=1,
        cohort_size=2,
        registration_window_s=100.0,
        model=TINY,
        data=dataclasses.replace(FedConfig().data, img_size=16),
    )
    variables = create_train_state(jax.random.key(0), TINY).variables
    blob = tree_to_bytes(variables)
    state = R.initial_state(cfg, variables)
    state, _ = R.transition(state, R.Ready(cname="a", now=0.0))
    state, _ = R.transition(state, R.Ready(cname="b", now=1.0))
    state, _ = R.transition(
        state, R.TrainDone(cname="a", round=1, blob=blob, num_samples=4, now=3.0)
    )
    state, _ = R.transition(
        state, R.TrainDone(cname="b", round=1, blob=blob, num_samples=4, now=5.0)
    )
    entry = state.history[0]
    assert entry["wall_clock_s"] == 4.0  # round opened at now=1.0 (cohort full)
    assert entry["bytes_received"] == 2 * len(blob)
    assert entry["bytes_broadcast"] > 0
    # history entries are JSON-serializable (checkpoint meta requirement)
    json.dumps(entry)


# ---- TensorBoard event-file export (obs/tb.py) ----


def test_tb_writer_roundtrip_and_crc(tmp_path):
    from fedcrack_tpu.obs import SummaryWriter, read_scalars

    with SummaryWriter(tmp_path) as w:
        w.add_scalar("round/loss", 0.5, step=1)
        w.add_scalar("round/loss", 0.25, step=2)
        w.add_scalar("round/iou", 0.75, step=2)
        path = w.path
    got = read_scalars(path)
    assert got == [
        ("round/loss", 0.5, 1),
        ("round/loss", 0.25, 2),
        ("round/iou", 0.75, 2),
    ]
    # a flipped byte in any record must be detected, not silently parsed
    import pytest as _pytest

    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    bad = tmp_path / "corrupt" / "events.out.tfevents.0.x"
    bad.parent.mkdir()
    bad.write_bytes(bytes(blob))
    with _pytest.raises(ValueError, match="CRC"):
        read_scalars(bad)


def test_tb_file_loads_in_real_tensorboard(tmp_path):
    """The acceptance bar: TensorBoard itself (event_accumulator) must read
    our hand-encoded event file — tags, values, steps."""
    from fedcrack_tpu.obs import SummaryWriter

    with SummaryWriter(tmp_path) as w:
        for step, loss in enumerate([0.9, 0.5, 0.3], start=1):
            w.add_scalar("round/loss", loss, step=step)
        w.add_scalar("round/iou", 0.42, step=3)

    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(str(tmp_path))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == {"round/loss", "round/iou"}
    losses = acc.Scalars("round/loss")
    assert [e.step for e in losses] == [1, 2, 3]
    np.testing.assert_allclose([e.value for e in losses], [0.9, 0.5, 0.3], rtol=1e-6)
    (iou,) = acc.Scalars("round/iou")
    assert iou.step == 3 and abs(iou.value - 0.42) < 1e-6


def test_metrics_logger_tees_tb_scalars(tmp_path):
    from fedcrack_tpu.obs import MetricsLogger, read_scalars

    tb_dir = tmp_path / "tb"
    with MetricsLogger(tmp_path / "m.jsonl", tb_dir=tb_dir) as m:
        m.log("round", round=1, loss=0.5, iou=0.1, clients=["a"], note="x")
        m.log("round", round=2, loss=0.25, iou=0.3)
        m.log("session", enrolled=True)  # no step field -> no scalars
    (event_file,) = list(tb_dir.iterdir())
    got = read_scalars(event_file)
    by_key = {(tag, step): value for tag, value, step in got}
    assert by_key[("round/loss", 1)] == 0.5
    assert abs(by_key[("round/iou", 2)] - 0.3) < 1e-6  # float32 storage
    # non-numeric fields and step-less records never become scalars
    assert not [t for t, _, _ in got if "clients" in t or "note" in t]
    assert not [t for t, _, _ in got if t.startswith("session/")]
    # the JSONL record of truth is untouched by the tee
    assert len(read_metrics(tmp_path / "m.jsonl", "round")) == 2


def test_tb_histogram_roundtrip(tmp_path):
    """add_histogram -> read_histograms preserves the distribution stats and
    bucket structure (equal-length limit/count arrays, counts sum to num)."""
    from fedcrack_tpu.obs import SummaryWriter, read_histograms

    rng = np.random.default_rng(0)
    values = rng.normal(0.0, 1.0, size=(7, 11)).astype(np.float32)
    with SummaryWriter(tmp_path) as w:
        w.add_histogram("weights/conv", values, step=2)
        w.add_histogram("weights/const", np.full(5, 3.25), step=2)  # degenerate
        w.add_histogram("weights/empty", np.array([]), step=2)
        path = w.path
    got = {tag: (h, step) for tag, h, step in read_histograms(path)}

    h, step = got["weights/conv"]
    assert step == 2
    assert h["num"] == values.size
    np.testing.assert_allclose(h["min"], values.min(), rtol=1e-6)
    np.testing.assert_allclose(h["max"], values.max(), rtol=1e-6)
    np.testing.assert_allclose(h["sum"], float(values.astype(np.float64).sum()), rtol=1e-6)
    np.testing.assert_allclose(
        h["sum_squares"], float(np.square(values.astype(np.float64)).sum()), rtol=1e-6
    )
    assert len(h["bucket"]) == len(h["bucket_limit"]) == 30
    assert sum(h["bucket"]) == values.size

    h_const, _ = got["weights/const"]
    assert h_const["num"] == 5 and sum(h_const["bucket"]) == 5
    assert h_const["bucket_limit"][0] > 3.25  # (lo, hi] interval non-empty
    h_empty, _ = got["weights/empty"]
    assert h_empty["num"] == 0

    # scalar reader ignores histogram events and vice versa
    from fedcrack_tpu.obs import read_scalars

    assert read_scalars(path) == []


def test_tb_histograms_load_in_real_tensorboard(tmp_path):
    """Acceptance bar for VERDICT r3 item 7: TensorBoard's own
    event_accumulator must read our histogram summaries back."""
    from fedcrack_tpu.obs import SummaryWriter

    rng = np.random.default_rng(1)
    with SummaryWriter(tmp_path) as w:
        for step in (1, 2):
            w.add_histogram("weights/dense", rng.normal(size=64) * step, step=step)

    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(
        str(tmp_path), size_guidance={event_accumulator.HISTOGRAMS: 0}
    )
    acc.Reload()
    assert "weights/dense" in acc.Tags()["histograms"]
    events = acc.Histograms("weights/dense")
    assert [e.step for e in events] == [1, 2]
    for e in events:
        v = e.histogram_value
        assert v.num == 64
        assert len(v.bucket) == len(v.bucket_limit)
        assert sum(v.bucket) == 64
        assert v.min <= v.max


def test_metrics_logger_tees_weight_histograms(tmp_path):
    """log_histograms flattens a pytree into per-layer histogram tags; the
    JSONL record of truth stays scalar-only."""
    from fedcrack_tpu.obs import MetricsLogger, read_histograms

    tree = {"conv": {"kernel": np.ones((3, 3)), "bias": np.zeros(4)}}
    tb_dir = tmp_path / "tb"
    with MetricsLogger(tmp_path / "m.jsonl", tb_dir=tb_dir) as m:
        assert m.tb_enabled
        m.log_histograms(3, tree, prefix="weights")
    (event_file,) = list(tb_dir.iterdir())
    got = {tag: step for tag, _, step in read_histograms(event_file)}
    assert got == {"weights/conv/kernel": 3, "weights/conv/bias": 3}
    assert (tmp_path / "m.jsonl").read_text() == ""

    with MetricsLogger(tmp_path / "m2.jsonl") as m:  # no tb_dir -> no-op
        assert not m.tb_enabled
        m.log_histograms(1, tree)
