"""Observability: JSONL metrics sink, round records, profiler hook.

The reference's observability is print banners + a disabled TensorBoard
upload path (SURVEY.md §5.1/§5.5); these tests pin the structured
replacement.
"""

import dataclasses
import json
import threading

import jax.numpy as jnp
import numpy as np

from fedcrack_tpu.configs import FedConfig, ModelConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_to_bytes
from fedcrack_tpu.obs import MetricsLogger, profiler_trace, read_metrics, stopwatch

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)


def test_metrics_logger_round_trip(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as m:
        m.log("round", round=1, loss=0.5, clients=["a", "b"])
        m.log("fit", loss=jnp.float32(0.25), n=np.int64(3))
    records = read_metrics(path)
    assert [r["kind"] for r in records] == ["round", "fit"]
    assert records[0]["clients"] == ["a", "b"]
    # jax/numpy scalars come back as plain JSON numbers, integers as ints
    assert records[1]["loss"] == 0.25
    assert records[1]["n"] == 3
    assert isinstance(records[1]["n"], int)
    assert all("t" in r and "ts" in r for r in records)


def test_metrics_logger_kind_filter_and_append(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as m:
        m.log("a", x=1)
    with MetricsLogger(path) as m:  # append, not truncate
        m.log("b", x=2)
    assert len(read_metrics(path)) == 2
    assert [r["x"] for r in read_metrics(path, kind="b")] == [2]


def test_metrics_logger_thread_safety(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLogger(path) as m:
        threads = [
            threading.Thread(target=lambda i=i: [m.log("t", i=i) for _ in range(50)])
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    records = read_metrics(path)
    assert len(records) == 200
    # every line parsed cleanly (no interleaved writes)
    for rec in records:
        assert rec["kind"] == "t"


def test_stopwatch_measures_time():
    with stopwatch() as w:
        pass
    assert 0.0 <= w["seconds"] < 1.0


def test_profiler_trace_disabled_is_noop():
    with profiler_trace(None):
        x = jnp.ones((4,)) + 1
    assert float(x.sum()) == 8.0


def test_profiler_trace_writes_events(tmp_path):
    logdir = tmp_path / "trace"
    with profiler_trace(str(logdir)):
        jnp.ones((8, 8)).sum().block_until_ready()
    produced = list(logdir.rglob("*"))
    assert produced, "profiler trace produced no files"


def test_round_history_carries_wall_clock_and_bytes():
    """The state machine's history entries now carry the observability
    fields (wall_clock_s, bytes_received, bytes_broadcast)."""
    from fedcrack_tpu.train.local import create_train_state

    import jax

    cfg = FedConfig(
        max_rounds=1,
        cohort_size=2,
        registration_window_s=100.0,
        model=TINY,
        data=dataclasses.replace(FedConfig().data, img_size=16),
    )
    variables = create_train_state(jax.random.key(0), TINY).variables
    blob = tree_to_bytes(variables)
    state = R.initial_state(cfg, variables)
    state, _ = R.transition(state, R.Ready(cname="a", now=0.0))
    state, _ = R.transition(state, R.Ready(cname="b", now=1.0))
    state, _ = R.transition(
        state, R.TrainDone(cname="a", round=1, blob=blob, num_samples=4, now=3.0)
    )
    state, _ = R.transition(
        state, R.TrainDone(cname="b", round=1, blob=blob, num_samples=4, now=5.0)
    )
    entry = state.history[0]
    assert entry["wall_clock_s"] == 4.0  # round opened at now=1.0 (cohort full)
    assert entry["bytes_received"] == 2 * len(blob)
    assert entry["bytes_broadcast"] > 0
    # history entries are JSON-serializable (checkpoint meta requirement)
    json.dumps(entry)
