"""Multi-host bring-up (`parallel/multihost.py`, SURVEY.md §5.8).

Unit tests drive the resolution/error branches with a faked
``jax.distributed``; the slow test is the real thing — two OS processes
joined through ``jax.distributed.initialize`` over loopback (Gloo), with a
cross-process psum over a 2-device mesh spanning both.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

from fedcrack_tpu.parallel.multihost import (
    global_mesh_devices,
    initialize_if_needed,
    is_coordinator,
)


@pytest.fixture
def not_initialized(monkeypatch):
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False, raising=False)


def test_explicit_args_must_be_complete(not_initialized):
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed("10.0.0.1:9999")
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed("10.0.0.1:9999", num_processes=4)
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed("10.0.0.1:9999", num_processes=4, process_id=-1)


def test_env_var_resolution(not_initialized, monkeypatch):
    calls = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.update(kw)
    )
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:9999")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    assert initialize_if_needed() is True
    assert calls == {
        "coordinator_address": "10.0.0.1:9999",
        "num_processes": 4,
        "process_id": 2,
    }


def test_env_var_incomplete_raises(not_initialized, monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:9999")
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed()


def test_autodetect_failure_means_single_host(not_initialized, monkeypatch):
    def raise_value_error():
        raise ValueError("no cluster metadata")

    monkeypatch.setattr(jax.distributed, "initialize", raise_value_error)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_if_needed() is False


def test_already_initialized_short_circuits(monkeypatch):
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True, raising=False)

    def boom(**kw):
        raise AssertionError("initialize must not be called again")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    assert initialize_if_needed() is True
    # and it must NOT touch jax.process_count() before deciding: doing so
    # initializes the XLA backend, after which a real initialize() raises
    # ("must be called before any JAX calls") — the bug that kept this
    # module from ever running multi-process.


def test_helpers_single_process():
    assert is_coordinator()  # process 0 by convention
    devs = global_mesh_devices()
    assert devs == sorted(devs, key=lambda d: (d.process_index, d.id))
    assert len(devs) == jax.device_count()


_WORKER = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
sys.path.insert(0, {repo!r})
from fedcrack_tpu.jaxcompat import shard_map
from fedcrack_tpu.parallel.multihost import (
    global_mesh_devices, initialize_if_needed, is_coordinator,
)
assert initialize_if_needed(f"127.0.0.1:{{port}}", n, pid)
assert jax.process_count() == n, jax.process_count()
assert is_coordinator() == (pid == 0)
devs = global_mesh_devices()
assert len(devs) == n, devs
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
mesh = Mesh(devs, ("clients",))
def f(v):
    return jax.lax.psum(v, "clients")
y = jax.jit(shard_map(f, mesh=mesh, in_specs=P(None), out_specs=P(None)))(
    jnp.ones((1,), jnp.float32)
)
total = float(np.asarray(jax.device_get(y))[0])
assert total == float(n), total
print(f"OK pid={{pid}} psum={{total}}")
"""


def _launch_two_workers(script_text: str, tmp_path, timeout: float) -> list[str]:
    """Run the worker script as 2 coordinated OS processes over a free
    loopback port; return their outputs. Encodes the hard-won launch rules:
    strip every JAX_/XLA_/PYTHONPATH env var (the image profile pre-binds the
    axon TPU platform), share the compilation cache, and never orphan a
    worker blocked in jax.distributed.initialize()."""
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_", "PYTHONPATH"))
    }
    env["JAX_COMPILATION_CACHE_DIR"] = "/tmp/jax_cache"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    return outs


_ROUND_WORKER = """
import sys
sys.path.insert(0, {repo!r})
import jax
from fedcrack_tpu.jaxcompat import ensure_cpu_devices
ensure_cpu_devices(4)
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.parallel import build_federated_round, stack_client_data
from fedcrack_tpu.parallel.multihost import global_mesh_devices, initialize_if_needed
from fedcrack_tpu.train.local import create_train_state

assert initialize_if_needed(f"127.0.0.1:{{port}}", n, pid)
assert jax.device_count() == 4 * n
devs = global_mesh_devices()
mesh = Mesh(np.asarray(devs, dtype=object).reshape(2 * n, 2), ("clients", "batch"))
tiny = ModelConfig(img_size=16, stem_features=4, encoder_features=(8,),
                   decoder_features=(8, 4))
steps, batch = 2, 4
# Each process synthesizes only ITS clients' shards (client index = global).
local = [synth_crack_batch(steps * batch, img_size=16, seed=c)
         for c in (2 * pid, 2 * pid + 1)]
li, lm = stack_client_data(local, steps, batch)
data_sharding = NamedSharding(mesh, P("clients", None, "batch"))
images = jax.make_array_from_process_local_data(data_sharding, li)
masks = jax.make_array_from_process_local_data(data_sharding, lm)
variables = jax.device_put(create_train_state(jax.random.key(0), tiny).variables,
                           NamedSharding(mesh, P()))
cshard = NamedSharding(mesh, P("clients"))
active = jax.device_put(np.ones(2 * n, np.float32), cshard)
n_samples = jax.device_put(np.full(2 * n, float(steps * batch), np.float32), cshard)
round_fn = build_federated_round(mesh, tiny, learning_rate=1e-3, local_epochs=1)
new_vars, metrics = round_fn(variables, images, masks, active, n_samples)
jax.block_until_ready(new_vars)
local_losses = np.asarray(metrics["loss"].addressable_shards[0].data)
assert np.all(np.isfinite(local_losses)), local_losses
leaf = jax.tree_util.tree_leaves(new_vars["params"])[1]
leafsum = float(np.asarray(leaf.addressable_shards[0].data, np.float64).sum())
print(f"OK pid={{pid}} leafsum={{leafsum:.9e}}")
"""


@pytest.mark.slow
def test_two_process_federated_round(tmp_path):
    """The full §5.8 capability: ONE federated round (4 clients x 2-way
    intra-client DP over 8 devices) spanning TWO OS processes — the FedAvg
    psum crosses the process boundary, each process stages only its own
    clients' data, and the resulting global model is identical on every
    process AND identical to the same round run single-process."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _launch_two_workers(_ROUND_WORKER.format(repo=repo), tmp_path, timeout=300)
    sums = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("OK pid="):
                pid = int(line.split("pid=")[1].split()[0])
                sums[pid] = float(line.split("leafsum=")[1])
    assert set(sums) == {0, 1}, outs
    # psum-FedAvg must leave every process with the identical global model.
    assert sums[0] == sums[1], sums

    # Golden cross-check: the same round on this process's own 8-device mesh.
    import numpy as np

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import build_federated_round, make_mesh, stack_client_data
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    steps, batch = 2, 4
    per_client = [synth_crack_batch(steps * batch, img_size=16, seed=c) for c in range(4)]
    images, masks = stack_client_data(per_client, steps, batch)
    variables = create_train_state(jax.random.key(0), tiny).variables
    round_fn = build_federated_round(make_mesh(4, 2), tiny, learning_rate=1e-3, local_epochs=1)
    new_vars, _ = round_fn(
        variables, images, masks, np.ones(4, np.float32),
        np.full(4, float(steps * batch), np.float32),
    )
    leaf = jax.tree_util.tree_leaves(new_vars["params"])[1]
    golden = float(np.asarray(leaf, np.float64).sum())
    assert sums[0] == pytest.approx(golden, rel=1e-5)


@pytest.mark.slow
def test_two_process_distributed_smoke(tmp_path):
    """The real §5.8 capability check: 2 OS processes form one logical JAX
    job (process_count()==2) and a psum crosses the process boundary."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _launch_two_workers(_WORKER.format(repo=repo), tmp_path, timeout=180)
    assert any("OK pid=0 psum=2.0" in o for o in outs), outs
    assert any("OK pid=1 psum=2.0" in o for o in outs), outs
