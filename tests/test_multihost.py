"""Multi-host bring-up (`parallel/multihost.py`, SURVEY.md §5.8).

Unit tests drive the resolution/error branches with a faked
``jax.distributed``; the slow test is the real thing — two OS processes
joined through ``jax.distributed.initialize`` over loopback (Gloo), with a
cross-process psum over a 2-device mesh spanning both.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

from fedcrack_tpu.parallel.multihost import (
    global_mesh_devices,
    initialize_if_needed,
    is_coordinator,
)


@pytest.fixture
def not_initialized(monkeypatch):
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False)


def test_explicit_args_must_be_complete(not_initialized):
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed("10.0.0.1:9999")
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed("10.0.0.1:9999", num_processes=4)
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed("10.0.0.1:9999", num_processes=4, process_id=-1)


def test_env_var_resolution(not_initialized, monkeypatch):
    calls = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.update(kw)
    )
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:9999")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    assert initialize_if_needed() is True
    assert calls == {
        "coordinator_address": "10.0.0.1:9999",
        "num_processes": 4,
        "process_id": 2,
    }


def test_env_var_incomplete_raises(not_initialized, monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:9999")
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="together"):
        initialize_if_needed()


def test_autodetect_failure_means_single_host(not_initialized, monkeypatch):
    def raise_value_error():
        raise ValueError("no cluster metadata")

    monkeypatch.setattr(jax.distributed, "initialize", raise_value_error)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_if_needed() is False


def test_already_initialized_short_circuits(monkeypatch):
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True)

    def boom(**kw):
        raise AssertionError("initialize must not be called again")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    assert initialize_if_needed() is True
    # and it must NOT touch jax.process_count() before deciding: doing so
    # initializes the XLA backend, after which a real initialize() raises
    # ("must be called before any JAX calls") — the bug that kept this
    # module from ever running multi-process.


def test_helpers_single_process():
    assert is_coordinator()  # process 0 by convention
    devs = global_mesh_devices()
    assert devs == sorted(devs, key=lambda d: (d.process_index, d.id))
    assert len(devs) == jax.device_count()


_WORKER = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
sys.path.insert(0, {repo!r})
from fedcrack_tpu.parallel.multihost import (
    global_mesh_devices, initialize_if_needed, is_coordinator,
)
assert initialize_if_needed(f"127.0.0.1:{{port}}", n, pid)
assert jax.process_count() == n, jax.process_count()
assert is_coordinator() == (pid == 0)
devs = global_mesh_devices()
assert len(devs) == n, devs
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
mesh = Mesh(devs, ("clients",))
def f(v):
    return jax.lax.psum(v, "clients")
y = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(None), out_specs=P(None)))(
    jnp.ones((1,), jnp.float32)
)
total = float(np.asarray(jax.device_get(y))[0])
assert total == float(n), total
print(f"OK pid={{pid}} psum={{total}}")
"""


@pytest.mark.slow
def test_two_process_distributed_smoke(tmp_path):
    """The real §5.8 capability check: 2 OS processes form one logical JAX
    job (process_count()==2) and a psum crosses the process boundary."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=repo))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_", "PYTHONPATH"))
    }
    env["JAX_COMPILATION_CACHE_DIR"] = "/tmp/jax_cache"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:  # never orphan a worker blocked in initialize()
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert any("OK pid=0 psum=2.0" in o for o in outs), outs
    assert any("OK pid=1 psum=2.0" in o for o in outs), outs
