"""fedlint — the static-analysis + runtime-sanitizer plane (round 11).

Three layers, each pinned here:

- **rules**: one tiny positive + one negative fixture per rule pack
  (determinism, durability, trace-safety, transport, lock-order, dead-code)
  so a rule regression fails on a 5-line snippet, not a 500-file tree;
- **engine**: suppression comments (`# fedlint: disable=RULE`) and the
  fingerprinted baseline file round-trip — including that EDITING a
  baselined line resurfaces the finding;
- **the gate**: the full rule set over the real `fedcrack_tpu/` tree with
  the committed `fedlint_baseline.json` reports ZERO findings (the tier-1
  CI contract: exit code 0), and the serve-plane lock graph stays acyclic;
- **sanitizers**: RecompileSentry counts jit-cache growth, the lock-order
  monitor raises on an inversion BEFORE it can deadlock, and
  `no_implicit_transfers` blocks implicit host<->device traffic while
  letting explicit device_put/get through.
"""

import json
import os
import threading

import pytest

pytestmark = pytest.mark.analysis

from fedcrack_tpu.analysis.engine import (
    Finding,
    LintEngine,
    ModuleSource,
    Severity,
    apply_baseline,
    load_baseline,
    make_baseline,
)
from fedcrack_tpu.analysis.rules import all_rules, rules_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src, path="fedcrack_tpu/fed/fixture.py", rules=None):
    engine = LintEngine(rules=rules if rules is not None else all_rules())
    return engine.lint_source(src, path=path)


def rule_ids(findings):
    return [f.rule for f in findings]


# ---- determinism pack ----


def test_det001_wall_clock_positive_and_negative():
    bad = "import time\ndeadline = time.time() + 5.0\n"
    assert "DET001" in rule_ids(lint(bad))
    good = "import time\ndeadline = time.monotonic() + 5.0\n"
    assert "DET001" not in rule_ids(lint(good))
    # datetime.now is the same class of bug.
    assert "DET001" in rule_ids(lint("import datetime\nts = datetime.datetime.now()\n"))


def test_det002_unseeded_random_positive_and_negative():
    assert "DET002" in rule_ids(lint("import random\nx = random.random()\n"))
    assert "DET002" in rule_ids(lint("import numpy as np\nx = np.random.uniform()\n"))
    assert "DET002" not in rule_ids(
        lint("import random\nrng = random.Random(7)\nx = rng.random()\n")
    )
    assert "DET002" not in rule_ids(
        lint("import numpy as np\nrng = np.random.default_rng(7)\nx = rng.uniform()\n")
    )


def test_det003_unsorted_listing_positive_and_negative():
    assert "DET003" in rule_ids(lint("import os\nnames = os.listdir(d)\n"))
    assert "DET003" in rule_ids(lint("import glob\nnames = glob.glob(p)\n"))
    assert "DET003" not in rule_ids(lint("import os\nnames = sorted(os.listdir(d))\n"))


def test_det004_set_iteration_positive_and_negative():
    bad = "s = set(names)\nout = []\nfor n in s:\n    out.append(n)\n"
    assert "DET004" in rule_ids(lint(bad))
    good = "s = set(names)\nout = []\nfor n in sorted(s):\n    out.append(n)\n"
    assert "DET004" not in rule_ids(rule_ids_src := lint(good)) or not rule_ids_src
    # Scoped: the same snippet outside fed/ckpt/serve does not fire.
    assert "DET004" not in rule_ids(lint(bad, path="fedcrack_tpu/tools/fixture.py"))


def test_det004_dict_view_into_serializer():
    bad = (
        "import msgpack\n"
        "blob = msgpack.packb([v for k, v in d.items()])\n"
    )
    assert "DET004" in rule_ids(lint(bad))
    good = (
        "import msgpack\n"
        "blob = msgpack.packb([v for k, v in sorted(d.items())])\n"
    )
    assert "DET004" not in rule_ids(lint(good))
    # A dict view that never reaches a serializer is fine (arrival order is
    # legitimate for, e.g., logging).
    assert "DET004" not in rule_ids(lint("for k, v in d.items():\n    log(k, v)\n"))


def test_det004_scopes_do_not_leak_across_functions():
    """A set-bound name in one function must not taint a same-named list in
    another — the per-scope walk stops at nested function boundaries."""
    src = (
        "def f1(xs):\n"
        "    s = set(xs)\n"
        "    return sorted(s)\n"
        "def f2(items):\n"
        "    s = [i * 2 for i in items]\n"
        "    out = []\n"
        "    for n in s:\n"
        "        out.append(n)\n"
        "    return out\n"
    )
    assert "DET004" not in rule_ids(lint(src))
    # Within ONE function the taint still tracks.
    leaky = (
        "def f(xs):\n"
        "    s = set(xs)\n"
        "    return [n for n in s]\n"
    )
    assert "DET004" in rule_ids(lint(leaky))


# ---- durability pack ----


def test_dur001_raw_ckpt_write_positive_and_negative():
    bad = 'with open(path, "wb") as f:\n    f.write(data)\n'
    assert "DUR001" in rule_ids(lint(bad, path="fedcrack_tpu/ckpt/fixture.py"))
    # Read mode is not a torn-write hazard.
    good = 'with open(path, "rb") as f:\n    data = f.read()\n'
    assert "DUR001" not in rule_ids(lint(good, path="fedcrack_tpu/ckpt/fixture.py"))
    # Outside ckpt/, a scratch write with no durable-state hint is fine...
    scratch = 'with open(report, "w") as f:\n    f.write(text)\n'
    assert "DUR001" not in rule_ids(lint(scratch, path="fedcrack_tpu/tools/fx.py"))
    # ...but a serialized-tree write is a checkpoint by any name.
    tree = (
        'with open(out, "wb") as f:\n'
        "    f.write(tree_to_bytes(variables))\n"
    )
    assert "DUR001" in rule_ids(lint(tree, path="fedcrack_tpu/tools/fx.py"))


# ---- trace-safety pack ----


def test_trace001_host_op_in_jitted_fn():
    bad = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    print(x)\n"
        "    return x * 2\n"
    )
    assert "TRACE001" in rule_ids(lint(bad, path="fedcrack_tpu/parallel/fx.py"))
    # .item() and np.* are the implicit-transfer class.
    item = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x.sum().item()\n"
    )
    assert "TRACE001" in rule_ids(lint(item, path="fedcrack_tpu/parallel/fx.py"))
    # Host ops in an untraced function are legitimate driver code.
    good = "def driver(x):\n    print(x)\n    return x\n"
    assert "TRACE001" not in rule_ids(lint(good, path="fedcrack_tpu/parallel/fx.py"))
    # Scope: outside parallel//serve-engine the rule stays quiet.
    assert "TRACE001" not in rule_ids(lint(bad, path="fedcrack_tpu/obs/fx.py"))


def test_trace001_fn_passed_to_scan_and_nested_defs():
    bad = (
        "import jax\n"
        "def body(carry, x):\n"
        "    import numpy as np\n"
        "    return carry, np.sum(x)\n"
        "def run(xs):\n"
        "    return jax.lax.scan(body, 0, xs)\n"
    )
    assert "TRACE001" in rule_ids(lint(bad, path="fedcrack_tpu/parallel/fx.py"))


# ---- transport pack ----


def test_trans001_unaudited_retry_positive_and_negative():
    bad = (
        "import grpc\n"
        "def call(stub):\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            return stub.Do()\n"
        "        except grpc.RpcError:\n"
        "            continue\n"
    )
    assert "TRANS001" in rule_ids(lint(bad, path="fedcrack_tpu/transport/fx.py"))
    good = (
        "import grpc\n"
        "def call(stub):\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            return stub.Do()\n"
        "        except grpc.RpcError as e:\n"
        "            if e.code() in NON_RETRYABLE_CODES:\n"
        "                raise\n"
        "            continue\n"
    )
    assert "TRANS001" not in rule_ids(lint(good, path="fedcrack_tpu/transport/fx.py"))
    # A handler outside any loop is not a retry.
    one_shot = (
        "import grpc\n"
        "def call(stub):\n"
        "    try:\n"
        "        return stub.Do()\n"
        "    except grpc.RpcError:\n"
        "        return None\n"
    )
    assert "TRANS001" not in rule_ids(lint(one_shot, path="fedcrack_tpu/transport/fx.py"))


def test_trans002_unknown_status_code():
    # The reference's `grcp.`-typo class: resolved only on the error path.
    bad = "import grpc\ncode = grpc.StatusCode.UNAVAILIBLE\n"
    assert "TRANS002" in rule_ids(lint(bad, path="fedcrack_tpu/tools/fx.py"))
    good = "import grpc\ncode = grpc.StatusCode.UNAVAILABLE\n"
    assert "TRANS002" not in rule_ids(lint(good, path="fedcrack_tpu/tools/fx.py"))


# ---- compress pack ----

def test_comp001_frame_decode_must_feed_validate_update():
    bad = (
        "from fedcrack_tpu.compress import decode_update\n"
        "def take(blob, state):\n"
        "    tree, frame = decode_update(blob, state.template, base)\n"
        "    return aggregate(tree)\n"
    )
    assert "COMP001" in rule_ids(lint(bad, path="fedcrack_tpu/fed/fx.py"))
    good = (
        "from fedcrack_tpu.compress import decode_update\n"
        "from fedcrack_tpu.fed.serialization import validate_update\n"
        "def take(blob, state):\n"
        "    tree, frame = decode_update(blob, state.template, base)\n"
        "    problem = validate_update(to_bytes(tree), state.template)\n"
        "    return None if problem else aggregate(tree)\n"
    )
    assert "COMP001" not in rule_ids(lint(good, path="fedcrack_tpu/fed/fx.py"))
    # The decoder layer composing its own parses is exempt: decode_update
    # returns trees, it does not feed the aggregator.
    layer = (
        "def decode_update(blob, template, base):\n"
        "    frame = decode_frame(blob)\n"
        "    return rebuild(frame, template, base)\n"
    )
    assert "COMP001" not in rule_ids(lint(layer, path="fedcrack_tpu/compress/fx.py"))
    # Outside fed/ and compress/ the rule does not apply.
    assert "COMP001" not in rule_ids(lint(bad, path="fedcrack_tpu/tools/fx.py"))


# ---- async-plane pack ----


def test_async001_unsorted_iteration_in_flush_path():
    """ASYNC001: in fed/, inside a function whose name marks the
    buffer-flush/staleness plane, every unsorted dict-view or set
    iteration is an ERROR — iteration order IS aggregation order there."""
    bad = (
        "def flush_buffer(buf):\n"
        "    return [v for k, v in buf.items()]\n"
    )
    assert "ASYNC001" in rule_ids(lint(bad))
    bad_set = (
        "def staleness_prune(versions):\n"
        "    keep = set(versions)\n"
        "    out = []\n"
        "    for v in keep:\n"
        "        out.append(v)\n"
        "    return out\n"
    )
    assert "ASYNC001" in rule_ids(lint(bad_set))
    good = (
        "def flush_buffer(buf):\n"
        "    return [v for k, v in sorted(buf.items())]\n"
    )
    assert "ASYNC001" not in rule_ids(lint(good))
    # A list iteration in a flush path is fine (lists carry their order).
    list_ok = (
        "def flush_buffer(entries):\n"
        "    return [e for e in entries]\n"
    )
    assert "ASYNC001" not in rule_ids(lint(list_ok))
    # Functions OUTSIDE the flush/buffer/staleness plane are DET004's
    # business, not this rule's.
    unrelated = (
        "def summarize(d):\n"
        "    return [v for v in d.values()]\n"
    )
    assert "ASYNC001" not in rule_ids(lint(unrelated))
    # Outside fed/ the rule does not apply.
    assert "ASYNC001" not in rule_ids(lint(bad, path="fedcrack_tpu/serve/fx.py"))


# ---- observability pack ----


def test_obs001_metric_name_literal_with_unit_suffix():
    """OBS001: registry metric names must be snake_case string literals
    with a unit suffix — computed or free-spelled names break the greppable
    catalog and can mint unbounded series."""
    good = (
        "from fedcrack_tpu.obs.registry import REGISTRY\n"
        "REGISTRY.counter('fed_updates_total', 'updates').inc()\n"
        "REGISTRY.histogram('serve_request_seconds', 'latency')\n"
        "REGISTRY.gauge('fed_buffer_fill_ratio', 'fill')\n"
    )
    assert "OBS001" not in rule_ids(lint(good))
    # Computed name: ungreppable, potentially unbounded.
    computed = (
        "from fedcrack_tpu.obs.registry import REGISTRY\n"
        "REGISTRY.counter(f'updates_{plane}_total', 'per-plane').inc()\n"
    )
    assert "OBS001" in rule_ids(lint(computed))
    # Free spelling: no unit suffix / not snake_case.
    assert "OBS001" in rule_ids(
        lint("registry.counter('updates_count', 'x')\n")
    )
    assert "OBS001" in rule_ids(lint("registry.gauge('FedUpdates_total', 'x')\n"))
    # name= keyword path is checked the same way.
    assert "OBS001" in rule_ids(
        lint("reg.histogram(name=make_name(), help='x')\n")
    )
    assert "OBS001" not in rule_ids(
        lint("reg.histogram(name='fed_flush_seconds', help='x')\n")
    )
    # Non-registry receivers with the same method names are not ours.
    assert "OBS001" not in rule_ids(lint("collections.Counter('abc')\n"))
    assert "OBS001" not in rule_ids(lint("stats.counter('whatever')\n"))


def test_obs002_span_name_dotted_literal():
    """OBS002 (round 16): tracing.span names must be dotted plane.verb
    string literals — the literal-name contract extended to spans, so the
    stitcher's plane census and `grep -r 'fed.flush'` both stay total."""
    good = (
        "from fedcrack_tpu.obs import spans as tracing\n"
        "with tracing.span('client.push', trace='fedtr-v0'):\n"
        "    pass\n"
        "with tracing.span('edge.flush_partial', links=[]):\n"
        "    pass\n"
        "with tracing.span(name='serve.batch'):\n"
        "    pass\n"
    )
    assert "OBS002" not in rule_ids(lint(good))
    # Computed name: the span catalog becomes ungreppable.
    computed = (
        "from fedcrack_tpu.obs import spans as tracing\n"
        "with tracing.span(f'serve.{verb}'):\n"
        "    pass\n"
    )
    assert "OBS002" in rule_ids(lint(computed))
    assert "OBS002" in rule_ids(lint("tracing.span(span_name)\n"))
    # Undotted / free-spelled: no plane prefix to stitch or census by.
    assert "OBS002" in rule_ids(lint("tracing.span('push')\n"))
    assert "OBS002" in rule_ids(lint("spans.span('Client.Push')\n"))
    assert "OBS002" in rule_ids(lint("tracing.span('fed.')\n"))
    # Non-tracing receivers with a span method are not ours.
    assert "OBS002" not in rule_ids(lint("rec.span('anything goes')\n"))
    assert "OBS002" not in rule_ids(lint("soup.span('html')\n"))


# ---- federation-health pack ----


def test_health001_client_label_outside_chokepoint():
    """HEALTH001 (round 18): a metric family labeled by a client axis
    mints one series per enrolled client — only health/ledger.py's bounded
    export (client_label / MAX_CLIENT_LABELS + _overflow) may do that."""
    bad = (
        "from fedcrack_tpu.obs.registry import REGISTRY\n"
        "REGISTRY.counter('fed_updates_total', 'per-client updates',\n"
        "                 labels=('client',)).labels(client=cname).inc()\n"
    )
    assert "HEALTH001" in rule_ids(lint(bad))
    # Every client-axis spelling is caught, on any metric kind / receiver
    # alias the OBS001 idiom covers.
    assert "HEALTH001" in rule_ids(
        lint("reg.gauge('fed_norm_ratio', 'x', labels=('cname',))\n")
    )
    assert "HEALTH001" in rule_ids(
        lint("registry.histogram('fed_lag_seconds', 'x',"
             " labels=['round', 'client_id'])\n")
    )
    # Bounded, non-client label axes stay fine.
    good = (
        "from fedcrack_tpu.obs.registry import REGISTRY\n"
        "REGISTRY.counter('fed_updates_total', 'x', labels=('result',))\n"
        "REGISTRY.gauge('serve_drift_psi_ratio', 'x',"
        " labels=('bucket', 'signal'))\n"
    )
    assert "HEALTH001" not in rule_ids(lint(good))
    # The chokepoint itself is exempt: its export path bounds cardinality.
    inside = "reg.gauge('fed_client_anomaly_score_ratio', 'x', labels=('client',))\n"
    assert "HEALTH001" not in rule_ids(
        lint(inside, path="fedcrack_tpu/health/ledger.py")
    )
    assert "HEALTH001" in rule_ids(
        lint(inside, path="fedcrack_tpu/fed/rounds.py")
    )
    # Non-registry receivers are not ours.
    assert "HEALTH001" not in rule_ids(
        lint("stats.counter('x_total', labels=('client',))\n")
    )
    # The live tree must route every client label through the chokepoint.
    engine = LintEngine(rules=[rules_by_id()["HEALTH001"]])
    modules = engine.load_modules(
        [os.path.join(REPO, "fedcrack_tpu")], rel_to=REPO
    )
    assert engine.lint_modules(modules) == []


# ---- aggregation-algebra pack ----


def test_agg001_fedavg_call_outside_the_algebra():
    """AGG001 (round 21): a direct ``fedavg(...)`` call in fed/ or
    parallel/ is a fifth copy of the aggregation fold — invisible to
    ``FedConfig.aggregation``, the quarantine gate, and every robust
    combine. Only the two chokepoints may spell the primitive."""
    bad = (
        "from fedcrack_tpu.fed.algorithms import fedavg\n"
        "avg = fedavg(trees, weights)\n"
    )
    # Default fixture path is fedcrack_tpu/fed/fixture.py: in scope.
    assert "AGG001" in rule_ids(lint(bad))
    # Attribute receivers (the aliasing idioms the planes actually used).
    assert "AGG001" in rule_ids(
        lint("from fedcrack_tpu.fed import rounds as R\n"
             "avg = R.fedavg(trees, w)\n")
    )
    # The mesh plane is in scope too.
    assert "AGG001" in rule_ids(
        lint(bad, path="fedcrack_tpu/parallel/fixture.py")
    )
    # The chokepoints themselves are exempt: the algebra's instances and
    # the primitive's home.
    assert "AGG001" not in rule_ids(
        lint(bad, path="fedcrack_tpu/fed/aggregation.py")
    )
    assert "AGG001" not in rule_ids(
        lint(bad, path="fedcrack_tpu/fed/algorithms.py")
    )
    # Outside fed//parallel/ (benches, tools, tests cross-checking the
    # algebra against the primitive) is deliberately out of scope.
    assert "AGG001" not in rule_ids(
        lint(bad, path="fedcrack_tpu/tools/fixture.py")
    )
    # The sanctioned route draws no finding.
    good = (
        "from fedcrack_tpu.fed import aggregation as _aggregation\n"
        "avg = _aggregation.fold(_aggregation.FedAvg(), triples)\n"
    )
    assert "AGG001" not in rule_ids(lint(good))
    # The live tree: every fed/ and parallel/ fold goes through the
    # algebra (the round-21 refactor's enforcement bit).
    engine = LintEngine(rules=[rules_by_id()["AGG001"]])
    modules = engine.load_modules(
        [os.path.join(REPO, "fedcrack_tpu")], rel_to=REPO
    )
    assert engine.lint_modules(modules) == []


# ---- lock-order pack (project scope: lint_modules, not lint_source) ----

CYCLE_SRC = """\
import threading

class S:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def fwd(self):
        with self.a:
            with self.b:
                pass

    def rev(self):
        with self.b:
            with self.a:
                pass
"""

ORDERED_SRC = CYCLE_SRC.replace(
    "        with self.b:\n            with self.a:\n                pass\n",
    "        with self.a:\n            with self.b:\n                pass\n",
)


def _lint_modules(named_sources):
    engine = LintEngine(rules=all_rules())
    modules = [ModuleSource(p, s) for p, s in named_sources]
    return engine.lint_modules(modules)


def test_lock001_cycle_detected_and_consistent_order_clean():
    findings = _lint_modules([("fedcrack_tpu/serve/fx.py", CYCLE_SRC)])
    assert "LOCK001" in rule_ids(findings)
    assert "a" in findings[rule_ids(findings).index("LOCK001")].message
    clean = _lint_modules([("fedcrack_tpu/serve/fx.py", ORDERED_SRC)])
    assert "LOCK001" not in rule_ids(clean)


def test_lock001_call_mediated_cycle_across_methods():
    src = """\
import threading

class S:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def takes_b(self):
        with self.b:
            pass

    def takes_a(self):
        with self.a:
            pass

    def fwd(self):
        with self.a:
            self.takes_b()

    def rev(self):
        with self.b:
            self.takes_a()
"""
    findings = _lint_modules([("fedcrack_tpu/serve/fx.py", src)])
    assert "LOCK001" in rule_ids(findings)


def test_lock_graph_json_payload():
    from fedcrack_tpu.analysis.rules.locks import build_lock_graph

    graph = build_lock_graph([ModuleSource("fedcrack_tpu/serve/fx.py", CYCLE_SRC)])
    payload = graph.to_json()
    assert {n["node_id"] for n in payload["nodes"]} == {
        "fedcrack_tpu/serve/fx.py::S.a",
        "fedcrack_tpu/serve/fx.py::S.b",
    }
    assert len(payload["edges"]) == 2  # a->b and b->a
    assert payload["cycles"] == [sorted(
        ["fedcrack_tpu/serve/fx.py::S.a", "fedcrack_tpu/serve/fx.py::S.b"]
    )]


# ---- dead-code pack ----


def test_dead001_unused_import_positive_and_negative():
    assert "DEAD001" in rule_ids(lint("import os\nx = 1\n"))
    assert "DEAD001" not in rule_ids(lint("import os\nx = os.getpid()\n"))
    # __init__.py re-export surface is exempt.
    assert "DEAD001" not in rule_ids(
        lint("from fedcrack_tpu import configs\n", path="fedcrack_tpu/__init__.py")
    )
    # `import x as x` and __all__ entries are explicit re-exports.
    assert "DEAD001" not in rule_ids(lint("from a import b as b\n"))
    assert "DEAD001" not in rule_ids(
        lint("from a import b\n__all__ = ['b']\n")
    )


def test_dead002_unreachable_positive_and_negative():
    bad = "def f():\n    return 1\n    x = 2\n"
    assert "DEAD002" in rule_ids(lint(bad))
    assert "DEAD002" in rule_ids(lint("if False:\n    x = 1\n"))
    good = "def f():\n    if c:\n        return 1\n    return 2\n"
    assert "DEAD002" not in rule_ids(lint(good))


# ---- serve-plane pack ----


def test_serve001_cache_lookup_without_version_is_error():
    bad_subscript = (
        "def f(self, h, w):\n"
        "    return self._cache[(h, w)]\n"
    )
    bad_get = (
        "def f(self, digest):\n"
        "    return self._cache.get(digest)\n"
    )
    bad_traced = (
        "def f(self, digest):\n"
        "    key = (digest, 0)\n"
        "    return tile_cache.get(key)\n"
    )
    for src in (bad_subscript, bad_get, bad_traced):
        findings = lint(src, path="fedcrack_tpu/serve/fixture.py")
        assert "SERVE001" in rule_ids(findings), src
        hit = findings[rule_ids(findings).index("SERVE001")]
        assert hit.severity is Severity.ERROR
        assert "hot swap" in hit.message


def test_serve001_versioned_keys_and_writes_are_clean():
    good_direct = (
        "def f(self, digest):\n"
        "    return self._cache[(self._version, digest)]\n"
    )
    good_traced = (
        "def f(self, version, digest):\n"
        "    key = (version, digest)\n"
        "    return self._cache.get(key)\n"
    )
    write_only = (
        "def f(self, digest, probs):\n"
        "    self._cache[digest] = probs\n"
        "    del self._cache[digest]\n"
    )
    non_cache = (
        "def f(self, digest):\n"
        "    return self._index.get(digest)\n"
    )
    for src in (good_direct, good_traced, write_only, non_cache):
        assert "SERVE001" not in rule_ids(
            lint(src, path="fedcrack_tpu/serve/fixture.py")
        ), src


def test_serve001_scoped_to_serve_tree():
    bad = "def f(cache, k):\n    return cache[k]\n"
    assert "SERVE001" in rule_ids(lint(bad, path="fedcrack_tpu/serve/fx.py"))
    assert "SERVE001" not in rule_ids(lint(bad, path="fedcrack_tpu/fed/fx.py"))


# ---- kernel-plane pack ----


def test_kern001_pallas_without_twin_positive_and_negative():
    bad = (
        "from jax.experimental import pallas as pl\n"
        "def launch(x):\n"
        "    return pl.pallas_call(_kernel, out_shape=o)(x)\n"
    )
    findings = lint(bad, path="fedcrack_tpu/kernels/fx.py")
    assert "KERN001" in rule_ids(findings)
    f = next(f for f in findings if f.rule == "KERN001")
    assert f.severity is Severity.ERROR
    # Twin form 1: an interpret= kwarg threaded to the interpreter path.
    good_interpret = (
        "from jax.experimental import pallas as pl\n"
        "def launch(x, interpret=False):\n"
        "    return pl.pallas_call(_kernel, out_shape=o, interpret=interpret)(x)\n"
    )
    assert "KERN001" not in rule_ids(
        lint(good_interpret, path="fedcrack_tpu/kernels/fx.py")
    )
    # Twin form 2: a plain-XLA reference function alongside the launch.
    good_reference = (
        "from jax.experimental import pallas as pl\n"
        "def _matmul_reference(x, w):\n"
        "    return x @ w\n"
        "def launch(x):\n"
        "    return pl.pallas_call(_kernel, out_shape=o)(x)\n"
    )
    assert "KERN001" not in rule_ids(
        lint(good_reference, path="fedcrack_tpu/kernels/fx.py")
    )


def test_kern001_fires_per_site_and_ignores_non_calls():
    bad_two_sites = (
        "from jax.experimental import pallas as pl\n"
        "def a(x):\n"
        "    return pl.pallas_call(_ka, out_shape=o)(x)\n"
        "def b(x):\n"
        "    return pl.pallas_call(_kb, out_shape=o)(x)\n"
    )
    findings = [
        f
        for f in lint(bad_two_sites, path="fedcrack_tpu/ops/fx.py")
        if f.rule == "KERN001"
    ]
    assert len(findings) == 2
    # Attribute reads and docstring mentions are not kernel launches.
    quiet = (
        '"""mentions pl.pallas_call in prose only."""\n'
        "from jax.experimental import pallas as pl\n"
        "launcher = pl.pallas_call\n"
    )
    assert "KERN001" not in rule_ids(lint(quiet, path="fedcrack_tpu/ops/fx.py"))


# ---- privacy-plane pack ----


def test_priv001_unseeded_rng_in_privacy_plane():
    """PRIV001 (round 23): inside privacy/ every draw must trace to an
    explicit seed — an argless generator constructor or an ambient entropy
    source silently breaks mask recovery and DP-noise replay."""
    path = "fedcrack_tpu/privacy/fixture.py"
    # Argless construction pulls OS entropy even though it LOOKS like the
    # seeded idiom.
    assert "PRIV001" in rule_ids(
        lint("import numpy as np\ng = np.random.default_rng()\n", path=path)
    )
    assert "PRIV001" in rule_ids(
        lint("import numpy as np\nbg = np.random.Philox()\n", path=path)
    )
    assert "PRIV001" in rule_ids(
        lint("import random\nr = random.Random()\n", path=path)
    )
    # Entropy-by-design sources are never acceptable, seeded or not.
    for src in (
        "import os\nseed = os.urandom(16)\n",
        "import secrets\nseed = secrets.randbits(64)\n",
        "import uuid\nseed = uuid.uuid4().int\n",
    ):
        assert "PRIV001" in rule_ids(lint(src, path=path))
    # The shipped idiom — sha256-rooted explicit seeds into Philox — is
    # clean (this is exactly what secagg.pair_mask / dpsgd do).
    good = (
        "import numpy as np\n"
        "gen = np.random.Generator(np.random.Philox(key=int(seed)))\n"
        "g2 = np.random.default_rng(42)\n"
        "ss = np.random.SeedSequence(1234)\n"
    )
    assert "PRIV001" not in rule_ids(lint(good, path=path))
    # Scoped: the same ambient draw outside privacy/ is DET-territory, not
    # PRIV001's.
    assert "PRIV001" not in rule_ids(
        lint("import os\nseed = os.urandom(16)\n",
             path="fedcrack_tpu/fed/rounds.py")
    )
    # The live privacy package itself must be clean under the rule.
    engine = LintEngine(rules=[rules_by_id()["PRIV001"]])
    modules = engine.load_modules(
        [os.path.join(REPO, "fedcrack_tpu", "privacy")], rel_to=REPO
    )
    assert engine.lint_modules(modules) == []


# ---- suppressions ----


def test_trailing_suppression_with_reason():
    src = "import time\nts = time.time()  # fedlint: disable=DET001 -- record ts\n"
    assert "DET001" not in rule_ids(lint(src))


def test_standalone_comment_guards_next_line():
    src = (
        "import time\n"
        "# fedlint: disable=DET001 -- record ts\n"
        "ts = time.time()\n"
    )
    assert "DET001" not in rule_ids(lint(src))


def test_suppression_is_rule_specific_and_line_specific():
    # Wrong rule id: the finding survives.
    src = "import time\nts = time.time()  # fedlint: disable=DET002\n"
    assert "DET001" in rule_ids(lint(src))
    # Different line: the finding survives.
    src = (
        "import time\n"
        "# fedlint: disable=DET001\n"
        "x = 1\n"
        "ts = time.time()\n"
    )
    assert "DET001" in rule_ids(lint(src))


def test_disable_file_and_disable_all():
    src = (
        "# fedlint: disable-file=DET001\n"
        "import time\n"
        "a = time.time()\n"
        "b = time.time()\n"
    )
    assert "DET001" not in rule_ids(lint(src))
    src = "import time\nts = time.time()  # fedlint: disable=all\n"
    assert rule_ids(lint(src)) == []


# ---- baseline ----


def test_baseline_round_trip_and_edit_invalidation(tmp_path):
    src = "import time\ndeadline = time.time() + 5\n"
    findings = lint(src)
    assert rule_ids(findings) == ["DET001"]
    payload = make_baseline(findings)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps(payload))
    loaded = load_baseline(str(bl))
    # Baselined: the same findings vanish.
    assert apply_baseline(findings, loaded) == []
    # Line numbers drift, content doesn't: a moved-but-identical line stays
    # baselined.
    moved = lint("import time\nx = 1\ny = 2\ndeadline = time.time() + 5\n")
    assert apply_baseline(moved, loaded) == []
    # EDITING the offending line invalidates the fingerprint.
    edited = lint("import time\ndeadline = time.time() + 60\n")
    assert rule_ids(apply_baseline(edited, loaded)) == ["DET001"]
    # Count-limited: a NEW second occurrence of a baselined line surfaces.
    doubled = lint(src + "deadline = time.time() + 5\n")
    assert len(apply_baseline(doubled, loaded)) == 1


def test_baseline_version_check(tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 999, "entries": {}}))
    with pytest.raises(ValueError):
        load_baseline(str(bl))


# ---- the tier-1 gate ----


def test_gate_zero_findings_over_fedcrack_tpu():
    """THE CI contract: the full rule set over the real tree, with the
    committed baseline, reports zero findings. A new wall-clock deadline,
    raw checkpoint write, unsorted listing, traced host op, unaudited
    retry, or lock-order cycle anywhere in fedcrack_tpu/ fails this test."""
    engine = LintEngine(rules=all_rules())
    baseline_path = os.path.join(REPO, "fedlint_baseline.json")
    assert os.path.exists(baseline_path), "fedlint_baseline.json must be committed"
    findings = engine.lint_paths(
        [os.path.join(REPO, "fedcrack_tpu")],
        rel_to=REPO,
        baseline=load_baseline(baseline_path),
    )
    assert findings == [], "non-baselined fedlint findings:\n" + "\n".join(
        str(f) for f in findings
    )


def test_committed_lock_graph_artifact_is_current_and_acyclic():
    """bench_runs/r11_serve_lock_graph.json is the acceptance artifact: it
    must match the graph the current tree produces (nodes + cycles) and
    stay acyclic — including the serve plane's three locks."""
    from fedcrack_tpu.analysis.rules.locks import build_lock_graph
    from fedcrack_tpu.tools.fedlint import repo_root

    artifact_path = os.path.join(REPO, "bench_runs", "r11_serve_lock_graph.json")
    with open(artifact_path, encoding="utf-8") as f:
        artifact = json.load(f)
    engine = LintEngine(rules=all_rules())
    lock_rule = rules_by_id()["LOCK001"]
    modules = [
        m
        for m in engine.load_modules(
            [os.path.join(repo_root(), "fedcrack_tpu")], rel_to=repo_root()
        )
        if lock_rule.applies_to(m.path)
    ]
    live = build_lock_graph(modules).to_json()
    assert artifact["cycles"] == [] and live["cycles"] == []
    assert {n["node_id"] for n in artifact["nodes"]} == {
        n["node_id"] for n in live["nodes"]
    }
    serve_locks = {n["node_id"] for n in live["nodes"] if "/serve/" in n["node_id"]}
    assert serve_locks == {
        "fedcrack_tpu/serve/batcher.py::MicroBatcher._lock",
        "fedcrack_tpu/serve/hot_swap.py::ModelVersionManager._lock",
        "fedcrack_tpu/serve/service.py::ServeService._lock",
        # Round 17: the fleet plane — commit-barrier slot lock, router
        # dispatch lock, rolling-SLO window lock (all leaf-or-acyclic;
        # router -> batcher is the graph's one sanctioned edge).
        "fedcrack_tpu/serve/fleet.py::FleetVersionManager._lock",
        "fedcrack_tpu/serve/router.py::FleetRouter._lock",
        "fedcrack_tpu/serve/router.py::RollingPercentiles._lock",
        # Round 19: the video-session manager's cross-session accounting
        # lock (leaf — per-session state is single-handler by design).
        "fedcrack_tpu/serve/stream.py::StreamSessionManager._lock",
        # Round 22: the elastic-fleet plane — autoscaler decision lock and
        # the shadow lane's mirror/controller locks (all leaves; neither
        # the scaler nor the shadow path holds a lock across fleet calls).
        "fedcrack_tpu/serve/autoscaler.py::FleetAutoscaler._lock",
        "fedcrack_tpu/serve/shadow.py::ShadowMirror._lock",
        "fedcrack_tpu/serve/shadow.py::ShadowController._lock",
    }


# ---- the CLI ----


def test_cli_list_rules_and_unknown_rule(capsys):
    from fedcrack_tpu.tools.fedlint import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("DET001", "DUR001", "TRACE001", "TRANS001", "LOCK001", "DEAD001"):
        assert rid in out
    assert main(["--rules", "NOPE999"]) == 2


def test_cli_findings_exit_code_json_and_baseline_cycle(tmp_path, capsys):
    from fedcrack_tpu.tools.fedlint import main

    bad = tmp_path / "fx.py"
    bad.write_text("import time\ndeadline = time.time() + 5\n")
    out_json = tmp_path / "findings.json"
    rc = main(
        ["--no-baseline", "--no-cache", "--json", str(out_json), str(bad)]
    )
    assert rc == 1
    payload = json.loads(out_json.read_text())
    assert [f["rule"] for f in payload["findings"]] == ["DET001"]
    assert payload["findings"][0]["fingerprint"]
    # --write-baseline, then the same tree under that baseline is clean.
    bl = tmp_path / "bl.json"
    assert main(["--no-cache", "--write-baseline", str(bl), str(bad)]) == 0
    assert main(["--no-cache", "--baseline", str(bl), str(bad)]) == 0
    capsys.readouterr()
    # --json - owns stdout: the payload parses as-is, human lines go to
    # stderr, so the documented `fedlint --json - | jq` pipeline works.
    rc = main(["--no-baseline", "--no-cache", "--json", "-", str(bad)])
    captured = capsys.readouterr()
    assert rc == 1
    piped = json.loads(captured.out)
    assert [f["rule"] for f in piped["findings"]] == ["DET001"]
    assert "DET001" in captured.err and "finding(s)" in captured.err


def test_cli_lock_graph_emission(tmp_path):
    from fedcrack_tpu.tools.fedlint import main

    out = tmp_path / "graph.json"
    rc = main(["--no-cache", "--lock-graph", str(out)])
    assert rc == 0
    payload = json.loads(out.read_text())
    assert set(payload) == {"nodes", "edges", "cycles"}
    assert payload["cycles"] == []


def test_cli_result_cache_round_trip(tmp_path, capsys):
    from fedcrack_tpu.tools.fedlint import main

    bad = tmp_path / "fx.py"
    bad.write_text("import time\ndeadline = time.time() + 5\n")
    cache = tmp_path / "cache"
    argv = ["--no-baseline", "--cache-dir", str(cache), str(bad)]
    assert main(argv) == 1          # cold: finds + caches
    assert (cache / "cache.json").exists()
    assert main(argv) == 1          # warm: same findings from cache
    out = capsys.readouterr().out
    assert "DET001" in out


# ---- runtime sanitizers ----


def test_recompile_sentry_counts_and_raises():
    import jax
    import numpy as np

    from fedcrack_tpu.analysis.sanitizers import RecompileError, RecompileSentry

    fn = jax.jit(lambda x: x * 2)
    if not RecompileSentry.supported(fn):
        pytest.skip("jit wrapper exposes no _cache_size on this jax build")
    sentry = RecompileSentry()
    sentry.watch("fn", fn)
    with sentry.expect(compiles=1):
        fn(jax.device_put(np.ones((4,), np.float32)))
    sentry.mark()
    fn(jax.device_put(np.zeros((4,), np.float32)))  # same signature: cached
    sentry.assert_steady()
    fn(jax.device_put(np.ones((8,), np.float32)))   # new shape: retrace
    with pytest.raises(RecompileError, match="unexpected recompiles"):
        sentry.assert_steady()
    sentry.mark()
    with pytest.raises(RecompileError, match="expected exactly 0"):
        with sentry.expect(compiles=0):
            fn(jax.device_put(np.ones((16,), np.float32)))


def test_recompile_sentry_rejects_non_jit_objects():
    from fedcrack_tpu.analysis.sanitizers import RecompileSentry

    with pytest.raises(TypeError, match="_cache_size"):
        RecompileSentry().watch("x", lambda: None)


def test_no_implicit_transfers_guard():
    import jax
    import numpy as np

    from fedcrack_tpu.analysis.sanitizers import no_implicit_transfers

    fn = jax.jit(lambda x: x + 1)
    host = np.ones((4,), np.float32)
    dev = jax.device_put(host)
    fn(dev)  # compile outside the guard
    with no_implicit_transfers():
        out = fn(dev)                      # device-resident: fine
        host_out = jax.device_get(out)     # explicit d2h: fine
    assert host_out.shape == (4,)
    with pytest.raises(Exception, match="[Dd]isallowed"):
        with no_implicit_transfers():
            fn(host)  # implicit h2d of a numpy arg


def test_lock_order_monitor_raises_on_inversion_with_stacks():
    from fedcrack_tpu.analysis.sanitizers import (
        LockOrderMonitor,
        LockOrderViolation,
        _MonitoredLock,
    )

    mon = LockOrderMonitor()
    a = _MonitoredLock("a", mon)
    b = _MonitoredLock("b", mon)
    with a:
        with b:
            pass
    assert ("a", "b") in mon.edges()
    with b:
        with pytest.raises(LockOrderViolation) as ei:
            a.acquire()
    # Both acquisition stacks in the report: actionable, not just "deadlock".
    assert "this acquisition" in str(ei.value)
    assert "earlier" in str(ei.value)
    # Same-order re-acquisition stays legal.
    with a:
        with b:
            pass


def test_make_lock_plain_in_production_monitored_in_debug(monkeypatch):
    import fedcrack_tpu.analysis.sanitizers as san

    monkeypatch.delenv("FEDCRACK_LOCK_DEBUG", raising=False)
    san.uninstall_monitor()
    lock = san.make_lock("x")
    assert isinstance(lock, type(threading.Lock()))
    try:
        mon = san.install_monitor()
        mlock = san.make_lock("x")
        assert isinstance(mlock, san._MonitoredLock)
        with mlock:
            pass
        assert mon is san._monitor
    finally:
        san.uninstall_monitor()


def test_serve_plane_locks_recorded_under_monitor(stack_free_engine=None):
    """The serve plane's three locks are built through make_lock: with a
    monitor installed, real traffic records named acquisitions (the debug
    twin of the static LOCK001 graph)."""
    import fedcrack_tpu.analysis.sanitizers as san
    from fedcrack_tpu.serve.batcher import StaticWeights

    san.uninstall_monitor()
    mon = san.install_monitor()
    try:
        from fedcrack_tpu.serve.hot_swap import ModelVersionManager

        class _NullEngine:
            def prepare(self, v):
                return v

        mgr = ModelVersionManager(_NullEngine(), {"params": {}})
        assert mgr.snapshot()[0] == 0
        assert isinstance(mgr._lock, san._MonitoredLock)
        assert isinstance(StaticWeights({}, 0).snapshot(), tuple)
    finally:
        san.uninstall_monitor()


# ---- fleet plane (round 22) ----


def test_fleet001_replica_set_mutation_outside_chokepoints():
    """Replica-set surgery in serve/ must route through ServeFleet — a
    convenience mutation desynchronizes the router's replica list from the
    fleet manager's weights slots."""
    append = "def grow(self):\n    self.router.replicas.append(object())\n"
    assert "FLEET001" in rule_ids(lint(append, path="fedcrack_tpu/serve/router.py"))
    delete = "def shrink(self):\n    del self.router.replicas[1]\n"
    assert "FLEET001" in rule_ids(lint(delete, path="fedcrack_tpu/serve/front.py"))
    slot = "def swap(self, r):\n    self.router.replicas[0] = r\n"
    assert "FLEET001" in rule_ids(lint(slot, path="fedcrack_tpu/serve/router.py"))
    # The lifecycle verbs ARE surgery wherever they're invoked in serve/.
    verb = "def tick(self):\n    self.fleet.remove_replica(2)\n"
    assert "FLEET001" in rule_ids(lint(verb, path="fedcrack_tpu/serve/shadow.py"))


def test_fleet001_chokepoints_and_plain_assign_exempt():
    # The fleet owns both lists; the autoscaler is the controller.
    verb = "def tick(self):\n    self.fleet.remove_replica(2)\n"
    assert "FLEET001" not in rule_ids(lint(verb, path="fedcrack_tpu/serve/fleet.py"))
    assert "FLEET001" not in rule_ids(
        lint(verb, path="fedcrack_tpu/serve/autoscaler.py")
    )
    # Constructing the initial list is legal everywhere — the router
    # receives the list it routes over; it just may not reshape it.
    assign = "def __init__(self, replicas):\n    self.replicas = list(replicas)\n"
    assert "FLEET001" not in rule_ids(
        lint(assign, path="fedcrack_tpu/serve/router.py")
    )
    # Outside serve/ (drills, benches driving kill_replica as the crash
    # hook) is deliberately out of scope.
    drill = "def crash(fleet):\n    fleet.router.kill_replica(1)\n"
    assert "FLEET001" not in rule_ids(
        lint(drill, path="fedcrack_tpu/tools/chaos_drill.py")
    )


def test_fleet001_own_serve_tree_is_clean():
    """The shipped serving plane obeys its own rule."""
    import glob

    engine = LintEngine(rules=[rules_by_id()["FLEET001"]])
    for path in sorted(glob.glob(os.path.join(REPO, "fedcrack_tpu", "serve", "*.py"))):
        with open(path) as f:
            src = f.read()
        rel = os.path.relpath(path, REPO)
        assert rule_ids(engine.lint_source(src, path=rel)) == [], rel
