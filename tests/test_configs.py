"""Config system: serialization round-trips, preset files, compatibility.

The reference has no config system (SURVEY.md §5.6); here every knob rides
one dataclass that must survive JSON round-trips (it travels in-band in the
protocol handshake) and load every checked-in preset — a rotten preset or a
broken from_dict kills the CLI entry points at startup.
"""

import glob
import json
import os

import pytest

from fedcrack_tpu.configs import DataConfig, FedConfig, ModelConfig, ServeConfig

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_every_checked_in_preset_loads():
    presets = sorted(glob.glob(os.path.join(ROOT, "configs", "*.json")))
    assert len(presets) >= 5, presets  # the five BASELINE configs
    for path in presets:
        with open(path) as f:
            cfg = FedConfig.from_json(f.read())
        # The cross-field invariant every loaded config must satisfy.
        assert cfg.data.img_size == cfg.model.img_size, path
        assert cfg.max_rounds >= 1, path


def test_json_round_trip_preserves_everything():
    cfg = FedConfig(
        max_rounds=7,
        cohort_size=3,
        fedprox_mu=0.01,
        pos_weight=5.0,
        server_optimizer="fedyogi",
        wire_dtype="bfloat16",
        best_path="/tmp/b.msgpack",
        model=ModelConfig(
            img_size=256,
            compute_dtype="bfloat16",
            stem_layout="s2d",
            res_layout="packed",
        ),
        data=DataConfig(img_size=256, batch_size=32, partition="skew"),
        serve=ServeConfig(
            bucket_sizes=(128, 256, 512),
            max_batch=16,
            max_delay_ms=3.0,
            swap_poll_s=0.5,
            compute_dtype="bfloat16",
            deadline_ms=100.0,
        ),
    )
    assert FedConfig.from_json(cfg.to_json()) == cfg


def test_old_configs_without_new_fields_still_load():
    """Forward compatibility: presets written before a field existed (e.g.
    best_path, pos_weight) must load with defaults, and unknown keys from a
    NEWER version must be ignored rather than crash an older server."""
    old = json.loads(FedConfig().to_json())
    for newer_field in ("best_path", "pos_weight", "server_optimizer", "tb_dir"):
        old.pop(newer_field, None)
    old["some_future_knob"] = 42
    old["model"]["another_future_knob"] = True
    cfg = FedConfig.from_dict(old)
    assert cfg.best_path == "" and cfg.pos_weight == 1.0
    assert cfg.server_optimizer == "avg"


def test_invalid_configs_rejected_at_construction():
    with pytest.raises(ValueError, match="multiple of 16"):
        ModelConfig(img_size=100)
    with pytest.raises(ValueError, match="wire_dtype"):
        FedConfig(wire_dtype="float16")
    with pytest.raises(ValueError, match="must match"):
        FedConfig(model=ModelConfig(img_size=256), data=DataConfig(img_size=128))


def test_serve_section_loads_with_defaults_and_survives_round_trip():
    """Presets written before round 10 carry no "serve" key — they must load
    with defaults; bucket_sizes must come back from JSON as a tuple (it is
    compared against mesh shapes and used as dict keys downstream)."""
    old = json.loads(FedConfig().to_json())
    old.pop("serve", None)
    cfg = FedConfig.from_dict(old)
    assert cfg.serve == ServeConfig()
    back = FedConfig.from_json(cfg.to_json())
    assert isinstance(back.serve.bucket_sizes, tuple)
    assert back.serve == cfg.serve
    with pytest.raises(ValueError, match="bucket size"):
        ServeConfig(bucket_sizes=(100,))


def test_encoder_features_survive_json_as_tuples():
    cfg = FedConfig(
        model=ModelConfig(encoder_features=(32, 64), decoder_features=(64, 32, 16, 8))
    )
    back = FedConfig.from_json(cfg.to_json())
    assert back.model.encoder_features == (32, 64)
    assert isinstance(back.model.encoder_features, tuple)
    assert back == cfg


def test_c16_lowp_kernels_preset_round_trips_with_kernel_plane():
    """The round-20 low-precision serving preset: fused_int8 predict behind
    the production install gate (IoU floor 0.98). kernel_plane must survive
    the JSON round-trip — it travels in-band like every other knob — and
    presets written before round 20 load with the "reference" default
    (covered by the forward-compat test above)."""
    path = os.path.join(ROOT, "configs", "c16_lowp_kernels.json")
    with open(path) as f:
        cfg = FedConfig.from_json(f.read())
    assert cfg.serve.kernel_plane == "fused_int8"
    assert cfg.serve.quant == "int8"  # fused planes require int8 sidecars
    assert cfg.serve.quant_iou_floor == 0.98  # the production floor
    assert FedConfig.from_json(cfg.to_json()) == cfg


def test_c17_robust_aggregation_preset_round_trips():
    """The round-21 robust-aggregation preset: trimmed-mean at the root
    plus the ledger-coupled quarantine gate. The new knobs travel in-band
    like every other FedConfig field; pre-r21 configs load with the
    bitwise-pinned "fedavg" default and quarantine disabled."""
    path = os.path.join(ROOT, "configs", "c17_robust_aggregation.json")
    with open(path) as f:
        cfg = FedConfig.from_json(f.read())
    assert cfg.aggregation == "trimmed_mean"
    assert cfg.trim_fraction == 0.2
    assert cfg.quarantine_z == 3.5  # the Iglewicz-Hoaglin alert cutoff
    assert FedConfig.from_json(cfg.to_json()) == cfg
    # A pre-r21 preset (no aggregation keys) keeps the seed behavior.
    with open(os.path.join(ROOT, "configs", "c13_buffered_async.json")) as f:
        old = FedConfig.from_json(f.read())
    assert old.aggregation == "fedavg" and old.quarantine_z == 0.0


def test_c19_privacy_preset_round_trips():
    """The round-23 privacy preset: DP-SGD (clip + noise + budget) and
    pairwise-mask secagg together. The preset must already satisfy
    secagg's composition constraints (fedavg / no quarantine / null codec
    / sync — validation would refuse it otherwise), and pre-r23 presets
    load with both planes off."""
    path = os.path.join(ROOT, "configs", "c19_privacy.json")
    with open(path) as f:
        cfg = FedConfig.from_json(f.read())
    assert cfg.secagg is True and cfg.secagg_bits == 24
    assert cfg.dp_clip_norm == 1.0 and cfg.dp_noise_multiplier == 1.1
    assert cfg.dp_epsilon_budget == 8.0 and cfg.dp_seed == 42
    # The constraints secagg's config validation enforces.
    assert cfg.aggregation == "fedavg" and cfg.quarantine_z == 0.0
    assert cfg.update_codec == "null" and cfg.mode == "sync"
    assert FedConfig.from_json(cfg.to_json()) == cfg
    # A pre-r23 preset (no privacy keys) keeps both planes off.
    with open(os.path.join(ROOT, "configs", "c17_robust_aggregation.json")) as f:
        old = FedConfig.from_json(f.read())
    assert old.secagg is False and old.dp_noise_multiplier == 0.0
