"""bench.py must actually run, end to end — round 1's lesson is that code
that only ever executes on the driver's hardware is code that silently rots.
The smoke run uses tiny env knobs and the CPU backend; it checks the JSON
contract the driver parses, not performance."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_smoke_emits_driver_contract():
    env = dict(os.environ)
    env.update(
        FEDCRACK_BENCH_FORCE_CPU="1",
        FEDCRACK_BENCH_STEPS="2",
        FEDCRACK_BENCH_BATCH="4",
        FEDCRACK_BENCH_REPS="1",
        FEDCRACK_BENCH_SIZES="32",
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=900,
        cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)

    # The driver's contract: one JSON line with these keys.
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["unit"] == "ms"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0

    # The round-2 additions: full sweep + decomposed host plane.
    detail = out["detail"]
    assert set(detail["sweep"]) == {"float32_32", "bfloat16_32"}
    for point in detail["sweep"].values():
        # Slope-based per-step can be None when the two-point fit fails on a
        # noisy host; the naive fallback must always be there.
        assert (point["per_step_ms"] or point["naive_per_step_ms"]) > 0
        assert point["flops_per_step"] > 0
    # Round-6 layout A/B: on a host fast enough to fund it the section must
    # carry the ab_pallas_bce artifact schema (per-variant dicts under
    # "impls", ratios as sibling keys); when the budget excluded it, the
    # skip must be RECORDED — never silent absence.
    layout_points = detail.get("layout_ab", {})
    if layout_points:
        for point in layout_points.values():
            assert all(isinstance(v, dict) for v in point["impls"].values())
            assert "reference" in point["impls"]
            assert point["flops_per_step_canonical"] > 0
    else:
        assert any(
            s["section"].startswith("layout_ab_") for s in detail["skipped"]
        )
    host = detail["host_plane"]
    reconstructed = (
        detail["n_clients"] * detail["steps"] * host["per_step_compute_ms"]
        + host["serialization_ms"]
        + host["host_fedavg_ms"]
        + host["dispatch_overhead_ms"]
    )
    # The decomposition must account for the measured total: dispatch is the
    # max(0, residual), so the parts either sum to the total (residual
    # positive) or over-cover it (compute estimate overshot a tiny CPU run —
    # they can never under-explain the round).
    assert reconstructed >= host["round_ms"] * 0.98
    assert detail["vs_baseline_compute_only"] > 0
    # Round-8 chaos-recovery drill: present with verified semantics + real
    # timings, or a RECORDED budget skip — never silent absence.
    chaos = detail.get("chaos_recovery")
    if chaos is not None and "error" not in chaos:
        assert chaos["resumed_mid_round"] and chaos["received_preserved"]
        assert chaos["recovered_avg_exact"] and chaos["history_gapless"]
        assert chaos["restore_s"] >= 0 and chaos["kill_to_recover_s"] > 0
    else:
        assert chaos is not None or any(
            s["section"] == "chaos_recovery" for s in detail["skipped"]
        )


@pytest.mark.slow
def test_bench_budget_skips_sections_but_still_emits():
    """The round-4 budget machinery under the round-5 section order: with an
    already-exhausted budget the mandatory flagship-size sweep still runs and
    the JSON still prints (rc 0), while every optional section — now
    INCLUDING the host plane, which round 5 demoted below the reference-scale
    headline (round-4 weak #1) — is skipped WITH a record under
    detail.skipped, never silently. vs_baseline is then honestly None rather
    than fabricated."""
    env = dict(os.environ)
    env.update(
        FEDCRACK_BENCH_FORCE_CPU="1",
        FEDCRACK_BENCH_STEPS="2",
        FEDCRACK_BENCH_BATCH="4",
        FEDCRACK_BENCH_REPS="1",
        FEDCRACK_BENCH_SIZES="32,48",  # 48 = the optional secondary size
        FEDCRACK_BENCH_BUDGET_S="1",  # exhausted before any optional section
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=900,
        cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    detail = out["detail"]
    # The mandatory sweep completed and priced the headline value.
    assert set(detail["sweep"]) == {"float32_32", "bfloat16_32"}
    assert out["value"] > 0
    # Exhausted budget: the host plane could not run, so the ratio is
    # honestly absent and the skip is RECORDED, not silently dropped.
    skipped = {s["section"]: s for s in detail["skipped"]}
    assert out["vs_baseline"] is None
    assert "host_plane" in skipped
    assert "sweep_48" in skipped
    assert "batch_curve" in skipped
    # The layout A/B prices a 2-variant comparison before spending anything
    # (even the long-scan tiling) and records its exclusion per dtype.
    assert "layout_ab_bfloat16_32" in skipped
    assert "layout_ab_float32_32" in skipped
    assert skipped["sweep_48"]["reason"] == "estimate exceeds remaining budget"
    assert detail["budget"]["budget_s"] == 1.0


# ---- tier-1-safe schema guards (round 7): artifact consumers key on these
# detail names; a rename must break CI here, not silently break dashboards
# and BASELINE.md updates downstream. No bench run needed — the module's
# declared schema is checked against its own emitting code and against the
# committed bench_runs/ artifacts. ----


def _import_bench():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_module", os.path.join(root, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_detail_schema_declares_contract_keys():
    bench = _import_bench()
    required = {
        "sweep",
        "skipped",
        "budget",
        "reference_scale",
        "layout_ab",
        "segmented_pipeline",
    }
    assert required <= set(bench.DETAIL_SCHEMA)
    assert {"round_ms", "round_plus_restage_ms", "staging_hidden_frac"} <= set(
        bench.REF_POINT_SCHEMA
    )
    # The schema cannot drift from the code that writes the payload: every
    # declared key must appear as a literal in bench.py's emitting code.
    with open(bench.__file__) as f:
        src = f.read()
    for key in required | set(bench.REF_POINT_SCHEMA):
        assert f'"{key}"' in src, f"schema key {key!r} never written by bench.py"


def test_validate_detail_typed_checks():
    bench = _import_bench()
    good = {
        "sweep": {"bfloat16_32": {}},
        "skipped": [],
        "budget": {"budget_s": 1.0},
        "reference_scale": {
            "bfloat16_128": {
                "round_ms": 7400.0,
                "round_plus_restage_ms": 20336.0,
                "staging_hidden_frac": 0.231,
            }
        },
        "segmented_pipeline": {
            "bfloat16_128": {
                "monolithic": {"round_ms": 7400.0, "staging_hidden_frac": 0.2},
                "segmented": {"round_ms": 7500.0, "staging_hidden_frac": None},
            }
        },
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({}) == []  # every section is optional
    bad = dict(good, skipped="oops")
    assert any("skipped" in v for v in bench.validate_detail(bad))
    bad2 = dict(
        good,
        reference_scale={"x": {"staging_hidden_frac": "0.2"}},
    )
    assert any("staging_hidden_frac" in v for v in bench.validate_detail(bad2))


def test_committed_bench_artifacts_satisfy_schema():
    """Every committed bench_runs/ artifact that carries a detail payload
    must validate against the declared schema — the contract holds
    retroactively, so consumers can parse any round's artifact."""
    bench = _import_bench()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    run_dir = os.path.join(root, "bench_runs")
    checked = 0
    for name in sorted(os.listdir(run_dir)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(run_dir, name)) as f:
            try:
                art = json.load(f)
            except ValueError:
                continue
        detail = art.get("detail") if isinstance(art, dict) else None
        if not isinstance(detail, dict):
            continue
        bad = bench.validate_detail(detail)
        assert not bad, f"{name}: {bad}"
        checked += 1
    assert checked >= 1, "no bench artifacts found to validate"
