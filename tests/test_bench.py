"""bench.py must actually run, end to end — round 1's lesson is that code
that only ever executes on the driver's hardware is code that silently rots.
The smoke run uses tiny env knobs and the CPU backend; it checks the JSON
contract the driver parses, not performance."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_smoke_emits_driver_contract():
    env = dict(os.environ)
    env.update(
        FEDCRACK_BENCH_FORCE_CPU="1",
        FEDCRACK_BENCH_STEPS="2",
        FEDCRACK_BENCH_BATCH="4",
        FEDCRACK_BENCH_REPS="1",
        FEDCRACK_BENCH_SIZES="32",
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=900,
        cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)

    # The driver's contract: one JSON line with these keys.
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["unit"] == "ms"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0

    # The round-2 additions: full sweep + decomposed host plane.
    detail = out["detail"]
    assert set(detail["sweep"]) == {"float32_32", "bfloat16_32"}
    for point in detail["sweep"].values():
        # Slope-based per-step can be None when the two-point fit fails on a
        # noisy host; the naive fallback must always be there.
        assert (point["per_step_ms"] or point["naive_per_step_ms"]) > 0
        assert point["flops_per_step"] > 0
    # Round-6 layout A/B: on a host fast enough to fund it the section must
    # carry the ab_pallas_bce artifact schema (per-variant dicts under
    # "impls", ratios as sibling keys); when the budget excluded it, the
    # skip must be RECORDED — never silent absence.
    layout_points = detail.get("layout_ab", {})
    if layout_points:
        for point in layout_points.values():
            assert all(isinstance(v, dict) for v in point["impls"].values())
            assert "reference" in point["impls"]
            assert point["flops_per_step_canonical"] > 0
    else:
        assert any(
            s["section"].startswith("layout_ab_") for s in detail["skipped"]
        )
    host = detail["host_plane"]
    reconstructed = (
        detail["n_clients"] * detail["steps"] * host["per_step_compute_ms"]
        + host["serialization_ms"]
        + host["host_fedavg_ms"]
        + host["dispatch_overhead_ms"]
    )
    # The decomposition must account for the measured total: dispatch is the
    # max(0, residual), so the parts either sum to the total (residual
    # positive) or over-cover it (compute estimate overshot a tiny CPU run —
    # they can never under-explain the round).
    assert reconstructed >= host["round_ms"] * 0.98
    assert detail["vs_baseline_compute_only"] > 0


@pytest.mark.slow
def test_bench_budget_skips_sections_but_still_emits():
    """The round-4 budget machinery under the round-5 section order: with an
    already-exhausted budget the mandatory flagship-size sweep still runs and
    the JSON still prints (rc 0), while every optional section — now
    INCLUDING the host plane, which round 5 demoted below the reference-scale
    headline (round-4 weak #1) — is skipped WITH a record under
    detail.skipped, never silently. vs_baseline is then honestly None rather
    than fabricated."""
    env = dict(os.environ)
    env.update(
        FEDCRACK_BENCH_FORCE_CPU="1",
        FEDCRACK_BENCH_STEPS="2",
        FEDCRACK_BENCH_BATCH="4",
        FEDCRACK_BENCH_REPS="1",
        FEDCRACK_BENCH_SIZES="32,48",  # 48 = the optional secondary size
        FEDCRACK_BENCH_BUDGET_S="1",  # exhausted before any optional section
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=900,
        cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    detail = out["detail"]
    # The mandatory sweep completed and priced the headline value.
    assert set(detail["sweep"]) == {"float32_32", "bfloat16_32"}
    assert out["value"] > 0
    # Exhausted budget: the host plane could not run, so the ratio is
    # honestly absent and the skip is RECORDED, not silently dropped.
    skipped = {s["section"]: s for s in detail["skipped"]}
    assert out["vs_baseline"] is None
    assert "host_plane" in skipped
    assert "sweep_48" in skipped
    assert "batch_curve" in skipped
    # The layout A/B prices a 2-variant comparison before spending anything
    # (even the long-scan tiling) and records its exclusion per dtype.
    assert "layout_ab_bfloat16_32" in skipped
    assert "layout_ab_float32_32" in skipped
    assert skipped["sweep_48"]["reason"] == "estimate exceeds remaining budget"
    assert detail["budget"]["budget_s"] == 1.0
