"""bench.py must actually run, end to end — round 1's lesson is that code
that only ever executes on the driver's hardware is code that silently rots.
The smoke run uses tiny env knobs and the CPU backend; it checks the JSON
contract the driver parses, not performance."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_smoke_emits_driver_contract(tmp_path):
    env = dict(os.environ)
    env.update(
        FEDCRACK_BENCH_FORCE_CPU="1",
        FEDCRACK_BENCH_STEPS="2",
        FEDCRACK_BENCH_BATCH="4",
        FEDCRACK_BENCH_REPS="1",
        FEDCRACK_BENCH_SIZES="32",
        # Per-test artifact path: the default is a fixed /tmp file, which
        # two concurrent bench runs would race on.
        FEDCRACK_BENCH_OUT=str(tmp_path / "payload.json"),
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=900,
        cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    # Round-9 output contract: the FINAL line is the compact summary (small
    # enough to survive tail-capture), the full payload is the line before
    # it and is also written to the artifact path the summary points at.
    summary = json.loads(lines[-1])
    assert summary["compact"] is True
    assert set(summary) >= {"metric", "value", "unit", "vs_baseline", "artifact"}
    assert summary["unit"] == "ms"
    assert summary["value"] > 0
    assert summary["vs_baseline"] > 0
    out = json.loads(lines[-2])
    assert out["value"] == summary["value"]
    if summary["artifact"]:
        with open(summary["artifact"]) as f:
            assert json.load(f)["value"] == out["value"]

    # The driver's contract: one JSON line with these keys.
    assert set(out) >= {"metric", "value", "unit", "vs_baseline"}
    assert out["unit"] == "ms"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0

    # The round-2 additions: full sweep + decomposed host plane.
    detail = out["detail"]
    assert set(detail["sweep"]) == {"float32_32", "bfloat16_32"}
    for point in detail["sweep"].values():
        # Slope-based per-step can be None when the two-point fit fails on a
        # noisy host; the naive fallback must always be there.
        assert (point["per_step_ms"] or point["naive_per_step_ms"]) > 0
        assert point["flops_per_step"] > 0
    # Round-6 layout A/B: on a host fast enough to fund it the section must
    # carry the ab_pallas_bce artifact schema (per-variant dicts under
    # "impls", ratios as sibling keys); when the budget excluded it, the
    # skip must be RECORDED — never silent absence.
    layout_points = detail.get("layout_ab", {})
    if layout_points:
        for point in layout_points.values():
            assert all(isinstance(v, dict) for v in point["impls"].values())
            assert "reference" in point["impls"]
            assert point["flops_per_step_canonical"] > 0
    else:
        assert any(
            s["section"].startswith("layout_ab_") for s in detail["skipped"]
        )
    host = detail["host_plane"]
    reconstructed = (
        detail["n_clients"] * detail["steps"] * host["per_step_compute_ms"]
        + host["serialization_ms"]
        + host["host_fedavg_ms"]
        + host["dispatch_overhead_ms"]
    )
    # The decomposition must account for the measured total: dispatch is the
    # max(0, residual), so the parts either sum to the total (residual
    # positive) or over-cover it (compute estimate overshot a tiny CPU run —
    # they can never under-explain the round).
    assert reconstructed >= host["round_ms"] * 0.98
    assert detail["vs_baseline_compute_only"] > 0
    # Round-8 chaos-recovery drill: present with verified semantics + real
    # timings, or a RECORDED budget skip — never silent absence.
    chaos = detail.get("chaos_recovery")
    if chaos is not None and "error" not in chaos:
        assert chaos["resumed_mid_round"] and chaos["received_preserved"]
        assert chaos["recovered_avg_exact"] and chaos["history_gapless"]
        assert chaos["restore_s"] >= 0 and chaos["kill_to_recover_s"] > 0
    else:
        assert chaos is not None or any(
            s["section"] == "chaos_recovery" for s in detail["skipped"]
        )
    # Round-12 update-compression A/B: present with the codec contract
    # intact (null byte-identical, compressed codecs strictly cheaper on
    # the wire at reference scale), or a RECORDED skip — never silent.
    comp = detail.get("update_compression")
    if comp is not None and "error" not in comp:
        assert comp["wire"]["null"]["null_identical"] is True
        assert comp["wire"]["null"]["bytes_per_round"] == comp["dense_update_bytes"]
        for codec in ("int8", "topk_delta"):
            assert comp["wire"][codec]["bytes_per_round"] < comp["dense_update_bytes"]
            assert comp["wire"][codec]["ratio_vs_null"] > 1.0
            assert len(comp["trajectory"][codec]["iou"]) == comp["rounds"]
    else:
        assert comp is not None or any(
            s["section"] == "update_compression" for s in detail["skipped"]
        )


@pytest.mark.slow
def test_bench_budget_skips_sections_but_still_emits(tmp_path):
    """The round-4 budget machinery under the round-5 section order: with an
    already-exhausted budget the mandatory flagship-size sweep still runs and
    the JSON still prints (rc 0), while every optional section — now
    INCLUDING the host plane, which round 5 demoted below the reference-scale
    headline (round-4 weak #1) — is skipped WITH a record under
    detail.skipped, never silently. vs_baseline is then honestly None rather
    than fabricated."""
    env = dict(os.environ)
    env.update(
        FEDCRACK_BENCH_FORCE_CPU="1",
        FEDCRACK_BENCH_STEPS="2",
        FEDCRACK_BENCH_BATCH="4",
        FEDCRACK_BENCH_REPS="1",
        FEDCRACK_BENCH_SIZES="32,48",  # 48 = the optional secondary size
        FEDCRACK_BENCH_BUDGET_S="1",  # exhausted before any optional section
        FEDCRACK_BENCH_OUT=str(tmp_path / "payload.json"),
    )
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=900,
        cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    summary = json.loads(lines[-1])
    assert summary["compact"] is True and summary["vs_baseline"] is None
    out = json.loads(lines[-2])
    detail = out["detail"]
    # The mandatory sweep completed and priced the headline value.
    assert set(detail["sweep"]) == {"float32_32", "bfloat16_32"}
    assert out["value"] > 0
    # Exhausted budget: the host plane could not run, so the ratio is
    # honestly absent and the skip is RECORDED, not silently dropped.
    skipped = {s["section"]: s for s in detail["skipped"]}
    assert out["vs_baseline"] is None
    assert "host_plane" in skipped
    assert "sweep_48" in skipped
    assert "batch_curve" in skipped
    # The layout A/B prices a 2-variant comparison before spending anything
    # (even the long-scan tiling) and records its exclusion per dtype.
    assert "layout_ab_bfloat16_32" in skipped
    assert "layout_ab_float32_32" in skipped
    assert skipped["sweep_48"]["reason"] == "estimate exceeds remaining budget"
    assert detail["budget"]["budget_s"] == 1.0


# ---- tier-1-safe schema guards (round 7): artifact consumers key on these
# detail names; a rename must break CI here, not silently break dashboards
# and BASELINE.md updates downstream. No bench run needed — the module's
# declared schema is checked against its own emitting code and against the
# committed bench_runs/ artifacts. ----


def _import_bench():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_module", os.path.join(root, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_detail_schema_declares_contract_keys():
    bench = _import_bench()
    required = {
        "sweep",
        "skipped",
        "budget",
        "reference_scale",
        "layout_ab",
        "segmented_pipeline",
        "resident_pool",
        "serving",
        "update_compression",
    }
    assert required <= set(bench.DETAIL_SCHEMA)
    # Round-10 serving arm: the SLO keys BASELINE.md reads must be declared.
    assert {"throughput_rps", "latency_ms", "swap", "dropped"} <= set(
        bench.SERVING_SCHEMA
    )
    assert {"round_ms", "round_plus_restage_ms", "staging_hidden_frac"} <= set(
        bench.REF_POINT_SCHEMA
    )
    # Round-12 compression arm: the bytes/timing keys BASELINE.md reads.
    assert {"dense_update_bytes", "rounds", "wire", "trajectory"} <= set(
        bench.COMPRESSION_SCHEMA
    )
    assert {"bytes_per_round", "ratio_vs_null", "encode_ms", "decode_ms"} <= set(
        bench.COMPRESSION_WIRE_SCHEMA
    )
    # Round-17 serve-fleet arm: the grid/swap/shed keys BASELINE.md reads.
    assert {"grid", "swap", "shed", "quant_gate"} <= set(bench.SERVE_FLEET_SCHEMA)
    assert {"replicas", "quant", "throughput_rps", "p95_ms"} <= set(
        bench.SERVE_FLEET_ARM_SCHEMA
    )
    # Round-19 video-serving arm: the effective-throughput + identity keys
    # BASELINE.md "Round 19" reads.
    assert {
        "effective_speedup",
        "effective_img_per_s",
        "speedup_target_met",
        "identity",
        "swap",
        "metrics_in_exposition",
    } <= set(bench.VIDEO_SERVING_SCHEMA)
    # The schema cannot drift from the code that writes the payload: every
    # declared key must appear as a literal in bench.py's emitting code.
    with open(bench.__file__) as f:
        src = f.read()
    for key in (
        required
        | set(bench.REF_POINT_SCHEMA)
        | set(bench.SERVING_SCHEMA)
        | set(bench.COMPRESSION_SCHEMA)
        | set(bench.COMPRESSION_WIRE_SCHEMA)
        | set(bench.SERVE_FLEET_SCHEMA)
        | set(bench.SERVE_FLEET_ARM_SCHEMA)
        | set(bench.VIDEO_SERVING_SCHEMA)
    ):
        assert f'"{key}"' in src, f"schema key {key!r} never written by bench.py"


def test_validate_detail_typed_checks():
    bench = _import_bench()
    good = {
        "sweep": {"bfloat16_32": {}},
        "skipped": [],
        "budget": {"budget_s": 1.0},
        "reference_scale": {
            "bfloat16_128": {
                "round_ms": 7400.0,
                "round_plus_restage_ms": 20336.0,
                "staging_hidden_frac": 0.231,
            }
        },
        "segmented_pipeline": {
            "bfloat16_128": {
                "monolithic": {"round_ms": 7400.0, "staging_hidden_frac": 0.2},
                "segmented": {"round_ms": 7500.0, "staging_hidden_frac": None},
            }
        },
        "resident_pool": {
            "bfloat16_128": {
                "streamed": {"round_ms": 7400.0, "round_plus_restage_ms": 20336.0},
                "resident": {"round_ms": 7420.0, "round_plus_restage_ms": 7500.0},
            }
        },
        "serving": {
            "throughput_rps": 41.5,
            "latency_ms": {"p50": 120.0, "p95": 180.0, "p99": 220.0},
            "requests": {"total": 128, "completed": 128},
            "batcher": {"batches": 20},
            "swap": {"to_version": 1, "load_ms": 35.0, "gap_ms": 4.0},
            "dropped": 0,
        },
        "update_compression": {
            "dense_update_bytes": 8236134,
            "rounds": 3,
            "wire": {
                "null": {
                    "bytes_per_round": 8236134,
                    "ratio_vs_null": None,
                    "encode_ms": 0.001,
                    "decode_ms": 180.0,
                    "null_identical": True,
                },
                "int8": {
                    "bytes_per_round": 789082,
                    "ratio_vs_null": 10.44,
                    "encode_ms": 92.0,
                    "decode_ms": 20.0,
                },
            },
            "trajectory": {"null": {"iou": [0.1, 0.2, 0.3]}},
        },
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({}) == []  # every section is optional
    # A serving section that errored out is exempt from the typed contract…
    assert bench.validate_detail({"serving": {"error": "boom"}}) == []
    # …but a present one must carry every declared key with the right type.
    assert any(
        "serving" in v for v in bench.validate_detail({"serving": {"dropped": 0}})
    )
    bad_serving = dict(good, serving=dict(good["serving"], dropped="none"))
    assert any("serving['dropped']" in v for v in bench.validate_detail(bad_serving))
    bad = dict(good, skipped="oops")
    assert any("skipped" in v for v in bench.validate_detail(bad))
    bad2 = dict(
        good,
        reference_scale={"x": {"staging_hidden_frac": "0.2"}},
    )
    assert any("staging_hidden_frac" in v for v in bench.validate_detail(bad2))
    bad3 = dict(
        good,
        resident_pool={"x": {"resident": {"round_ms": "slow"}}},
    )
    assert any("resident_pool" in v for v in bench.validate_detail(bad3))
    # Round-17 serve-fleet arm: error-arm exempt, present arm fully typed,
    # per-arm grid points typed, non-dict points reported never crashed.
    assert bench.validate_detail({"serve_fleet": {"error": "boom"}}) == []
    fleet_ok = {
        "serve_fleet": {
            "buckets": [128, 256],
            "max_batch": 8,
            "grid": {
                "r2_int8": {
                    "replicas": 2,
                    "quant": "int8",
                    "served_quant": True,
                    "requests": 64,
                    "completed": 64,
                    "throughput_rps": 120.5,
                    "p50_ms": 30.0,
                    "p95_ms": 55.0,
                }
            },
            "swap": {"pause_ms": 0.3, "torn_versions": 0, "zero_torn": True},
            "shed": {"total": 7, "by_reason": {"queue_bound": 7}},
            "quant_gate": {"passed": True, "iou": 0.99},
        }
    }
    assert bench.validate_detail(fleet_ok) == []
    assert any(
        "serve_fleet" in v for v in bench.validate_detail({"serve_fleet": {"grid": {}}})
    )
    fleet_bad = {
        "serve_fleet": dict(
            fleet_ok["serve_fleet"], grid={"r1_bf16": {"replicas": "two"}}
        )
    }
    assert any(
        "serve_fleet.grid" in v for v in bench.validate_detail(fleet_bad)
    )
    fleet_bad2 = {
        "serve_fleet": dict(fleet_ok["serve_fleet"], grid={"r1_bf16": ["x"]})
    }
    assert any(
        "serve_fleet.grid['r1_bf16']" in v
        for v in bench.validate_detail(fleet_bad2)
    )
    # quant_gate None = quant disabled this run — legal.
    assert (
        bench.validate_detail(
            {"serve_fleet": dict(fleet_ok["serve_fleet"], quant_gate=None)}
        )
        == []
    )
    # Round-12 compression arm: error-arm exempt, present arm fully typed.
    assert bench.validate_detail({"update_compression": {"error": "boom"}}) == []
    assert any(
        "update_compression" in v
        for v in bench.validate_detail({"update_compression": {"wire": {}}})
    )
    bad4 = dict(
        good,
        update_compression=dict(
            good["update_compression"],
            wire={"int8": {"bytes_per_round": "many"}},
        ),
    )
    assert any("update_compression.wire" in v for v in bench.validate_detail(bad4))
    # a non-dict wire must be REPORTED, not crash the validator
    bad5 = dict(
        good,
        update_compression=dict(good["update_compression"], wire=["x"]),
    )
    assert any("wire" in v for v in bench.validate_detail(bad5))
    # ... and so must a non-dict per-codec wire POINT (r12 review fix:
    # previously a TypeError at `key not in point` aborted validation)
    bad6 = dict(
        good,
        update_compression=dict(good["update_compression"], wire={"int8": 42}),
    )
    assert any("update_compression.wire['int8']" in v
               for v in bench.validate_detail(bad6))
    # Round-15 observability arm: error-arm exempt; a present arm must carry
    # the soak contract (audit booleans typed, planes_covered a dict).
    assert bench.validate_detail({"observability": {"error": "boom"}}) == []
    assert any(
        "observability" in v
        for v in bench.validate_detail({"observability": {"audit": {}}})
    )
    obs_ok = {
        "observability": {
            "traffic_wall_s": 8.0,
            "storm_fired": True,
            "federation": {},
            "serve": {},
            "scrape": {"planes_covered": {"fed": True}},
            "spans": {},
            "audit": {
                "torn_versions": 0,
                "zero_torn_versions": True,
                "serve_healthy": True,
                "ef_mass_conserved": True,
                "statefile_restore_bit_identical": True,
                "watermarks_steady": True,
                "recompiles_since_warmup": 0,
                "clean": True,
            },
        }
    }
    assert bench.validate_detail(obs_ok) == []
    obs_bad = json.loads(json.dumps(obs_ok))
    obs_bad["observability"]["audit"]["torn_versions"] = "none"
    assert any(
        "observability.audit['torn_versions']" in v
        for v in bench.validate_detail(obs_bad)
    )
    obs_bad2 = json.loads(json.dumps(obs_ok))
    obs_bad2["observability"]["scrape"]["planes_covered"] = ["fed"]
    assert any(
        "planes_covered" in v for v in bench.validate_detail(obs_bad2)
    )
    # Round-16 tracing/watchdog arms: ABSENT is fine (r15 artifacts predate
    # them), but a present arm must carry the full sub-schema.
    assert bench.validate_detail(obs_ok) == []
    obs_r16 = json.loads(json.dumps(obs_ok))
    obs_r16["observability"]["tracing"] = {
        "records": 100, "traces": 5, "chains": 3, "n_complete": 1,
        "complete": True, "trace": "fedtr-v0",
        "planes_crossed": ["client", "fed", "serve"],
        "stages": ["client.push", "fed.flush", "serve.batch", "serve.swap"],
    }
    obs_r16["observability"]["watchdog"] = {
        "rules_evaluated": 6, "rules": ["a"], "evaluations": 9,
        "never_determinate": [], "all_rules_evaluated": True,
        "breaches": [], "clean": True,
    }
    assert bench.validate_detail(obs_r16) == []
    obs_r16_bad = json.loads(json.dumps(obs_r16))
    del obs_r16_bad["observability"]["tracing"]["complete"]
    assert any(
        "observability.tracing['complete']" in v
        for v in bench.validate_detail(obs_r16_bad)
    )
    obs_r16_bad2 = json.loads(json.dumps(obs_r16))
    obs_r16_bad2["observability"]["watchdog"]["breaches"] = 0
    assert any(
        "observability.watchdog['breaches']" in v
        for v in bench.validate_detail(obs_r16_bad2)
    )


def test_committed_r16_artifact_has_stitched_trace_and_watchdog_audit():
    """The round-16 acceptance pin: the committed soak/bench artifact holds
    at least one stitched trace whose chain crosses >= 3 planes (client,
    root/fed, serve) under a single trace id, and a clean machine-checked
    watchdog audit with every rule evaluated."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    run_dir = os.path.join(root, "bench_runs")
    candidates = [
        n for n in sorted(os.listdir(run_dir))
        if n.startswith("r16_") and n.endswith(".json")
    ]
    assert candidates, "no committed r16 artifact"
    with open(os.path.join(run_dir, candidates[0])) as f:
        art = json.load(f)
    obsy = art["detail"]["observability"]
    tr = obsy["tracing"]
    assert tr["complete"] and tr["n_complete"] >= 1
    assert tr["trace"].startswith("fedtr-v")
    assert {"client", "fed", "serve"} <= set(tr["planes_crossed"])
    for stage in ("fed.flush", "serve.swap", "serve.batch"):
        assert stage in tr["stages"], stage
    assert {"client.push", "edge.flush_partial"} & set(tr["stages"])
    wd = obsy["watchdog"]
    assert wd["clean"] and wd["all_rules_evaluated"] and wd["breaches"] == []
    assert wd["evaluations"] > 1 and wd["rules_evaluated"] >= 5
    assert obsy["audit"]["watchdog_clean"] and obsy["audit"]["clean"]


def test_compact_summary_last_line_parses():
    """Round-9 tail-capture fix: whatever size the full payload grows to,
    the FINAL stdout line must be a small, self-contained JSON summary —
    BENCH_r05.json's "parsed": null came from the monolithic payload line
    being truncated by tail-capture. Exercised without a bench run: a
    deliberately bloated payload must compact to a bounded line carrying
    the driver-contract keys."""
    bench = _import_bench()
    fat_detail = {k: {} for k in bench.DETAIL_SCHEMA if k != "skipped"}
    fat_detail["sweep"] = {f"p{i}": {"blob": "x" * 4096} for i in range(64)}
    fat_detail["skipped"] = [{"section": f"s{i}"} for i in range(16)]
    payload = {
        "metric": "m" * 500,
        "value": 123.4,
        "unit": "ms",
        "vs_baseline": 2.5,
        "detail": fat_detail,
        "interrupted": "SIGTERM",
        "schema_violations": ["a", "b"],
    }
    line = json.dumps(bench.compact_summary(payload, "/tmp/art.json"))
    assert len(line) < 4096, f"compact line is {len(line)} bytes"
    summary = json.loads(line)
    assert summary["compact"] is True
    assert set(summary) >= {"metric", "value", "unit", "vs_baseline", "artifact"}
    assert summary["value"] == 123.4 and summary["artifact"] == "/tmp/art.json"
    assert "resident_pool" in summary["sections"]
    assert "detail" not in summary  # the tree is exactly what gets truncated
    assert summary["skipped_n"] == 16
    assert summary["interrupted"] == "SIGTERM"
    assert summary["schema_violations_n"] == 2


def test_emit_prints_compact_summary_as_final_line(tmp_path, capsys, monkeypatch):
    """_emit's stdout contract end to end (in-process): full payload line,
    then the compact summary as the LAST line, with the full payload also
    written to the artifact path the summary points at."""
    bench = _import_bench()
    art = tmp_path / "payload.json"
    monkeypatch.setattr(bench, "BENCH_OUT", str(art))
    bench._set_payload("metric-string", 42.0, 1.5, {"sweep": {}, "skipped": []})
    bench._emit()
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    full = json.loads(lines[0])
    summary = json.loads(lines[-1])
    assert full["value"] == 42.0 and "detail" in full
    assert summary["compact"] is True and summary["value"] == 42.0
    assert summary["artifact"] == str(art)
    with open(art) as f:
        assert json.load(f) == full
    # Idempotence: a signal landing after the normal emit must not double-print.
    bench._emit()
    assert capsys.readouterr().out == ""


def test_committed_bench_artifacts_satisfy_schema():
    """Every committed bench_runs/ artifact that carries a detail payload
    must validate against the declared schema — the contract holds
    retroactively, so consumers can parse any round's artifact."""
    bench = _import_bench()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    run_dir = os.path.join(root, "bench_runs")
    checked = 0
    for name in sorted(os.listdir(run_dir)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(run_dir, name)) as f:
            try:
                art = json.load(f)
            except ValueError:
                continue
        detail = art.get("detail") if isinstance(art, dict) else None
        if not isinstance(detail, dict):
            continue
        bad = bench.validate_detail(detail)
        assert not bad, f"{name}: {bad}"
        checked += 1
    assert checked >= 1, "no bench artifacts found to validate"


def test_cohort_scale_schema_guard():
    """Round-13 cohort_scale arm: declared in DETAIL_SCHEMA, its keys
    written by bench.py, typed checks enforced, error-arm exempt."""
    bench = _import_bench()
    assert "cohort_scale" in bench.DETAIL_SCHEMA
    assert {"groups", "tree", "flat"} <= set(bench.COHORT_SCALE_SCHEMA)
    assert {"round_wall_s", "group_dispatches"} <= set(bench.COHORT_GROUP_SCHEMA)
    with open(bench.__file__) as f:
        src = f.read()
    for key in set(bench.COHORT_SCALE_SCHEMA) | set(bench.COHORT_GROUP_SCHEMA):
        assert f'"{key}"' in src, f"schema key {key!r} never written by bench.py"
    good = {
        "cohort_scale": {
            "groups": {"2": {"round_wall_s": 1.5, "group_dispatches": 2}},
            "tree": {"root_peak_blobs": 32},
            "flat": {"root_peak_blobs": 1024},
        }
    }
    assert bench.validate_detail(good) == []
    # error arm exempt (a failed section still emits a valid artifact)
    assert bench.validate_detail({"cohort_scale": {"error": "boom"}}) == []
    # missing required key reported
    assert any(
        "cohort_scale['flat'] missing" in v
        for v in bench.validate_detail(
            {"cohort_scale": {"groups": {}, "tree": {}}}
        )
    )
    # typed per-group point; a non-dict point is REPORTED, never a crash
    bad = {
        "cohort_scale": {
            "groups": {"2": {"round_wall_s": "slow", "group_dispatches": 2}},
            "tree": {},
            "flat": {},
        }
    }
    assert any("round_wall_s" in v for v in bench.validate_detail(bad))
    bad2 = {"cohort_scale": {"groups": {"2": 42}, "tree": {}, "flat": {}}}
    assert any("groups['2']" in v for v in bench.validate_detail(bad2))
    # compact summary lists the section like any other schema section
    summary = bench.compact_summary({"detail": good})
    assert "cohort_scale" in summary["sections"]


def test_async_federation_schema_guard():
    """Round-14 async_federation arm: declared in DETAIL_SCHEMA, its keys
    written by bench.py, storm arms typed, error-arm exempt."""
    bench = _import_bench()
    assert "async_federation" in bench.DETAIL_SCHEMA
    assert {"storm", "sync_equivalence", "recovery", "trajectory"} <= set(
        bench.ASYNC_FEDERATION_SCHEMA
    )
    assert {"updates_per_sec", "versions_per_min", "accepted_updates"} <= set(
        bench.ASYNC_STORM_ARM_SCHEMA
    )
    with open(bench.__file__) as f:
        src = f.read()
    for key in set(bench.ASYNC_FEDERATION_SCHEMA):
        assert f'"{key}"' in src, f"schema key {key!r} never written by bench.py"
    arm = {
        "wall_s": 1.0,
        "accepted_updates": 6,
        "global_versions": 3,
        "updates_per_sec": 6.0,
        "versions_per_min": 180.0,
    }
    good = {
        "async_federation": {
            "storm": {"sync": dict(arm), "buffered": dict(arm)},
            "sync_equivalence": {"bit_identical": True},
            "recovery": {"global_blob_bit_identical": True},
            "trajectory": {"buffered_final_loss": 0.01},
        }
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({"async_federation": {"error": "boom"}}) == []
    assert any(
        "async_federation['recovery'] missing" in v
        for v in bench.validate_detail(
            {
                "async_federation": {
                    "storm": {"sync": dict(arm), "buffered": dict(arm)},
                    "sync_equivalence": {},
                    "trajectory": {},
                }
            }
        )
    )
    # A missing or mistyped storm arm is REPORTED, never a crash.
    bad = {
        "async_federation": {
            "storm": {"sync": 42, "buffered": dict(arm, updates_per_sec="x")},
            "sync_equivalence": {},
            "recovery": {},
            "trajectory": {},
        }
    }
    violations = bench.validate_detail(bad)
    assert any("storm['sync']" in v for v in violations)
    assert any("updates_per_sec" in v for v in violations)
    summary = bench.compact_summary({"detail": good})
    assert "async_federation" in summary["sections"]


def test_video_serving_schema_guard():
    """Round-19 video-serving arm: error-arm exempt, a present arm fully
    typed, mistyped values reported never crashed, and the compact summary
    lists the section."""
    bench = _import_bench()
    good = {
        "video_serving": {
            "frame": {"size": 192, "frames": 20, "overlap_fraction": 0.9583},
            "stateless": {"wall_s": 0.55, "img_per_s": 36.2},
            "session": {"wall_s": 0.16, "img_per_s": 122.3, "hit_ratio": 0.74},
            "effective_speedup": 4.43,
            "effective_img_per_s": 160.5,
            "speedup_target_met": True,
            "identity": {"frames_checked": 20, "mismatches": 0, "ok": True},
            "swap": {"frame": 13, "identity_after_swap": True},
            "metrics_in_exposition": True,
            "grpc_smoke": {"frames_dropped": 0, "audit": {"ok": True}},
        }
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({"video_serving": {"error": "boom"}}) == []
    # grpc_smoke is nullable (the smoke must not void the in-process A/B).
    nosmoke = dict(good["video_serving"], grpc_smoke=None)
    assert bench.validate_detail({"video_serving": nosmoke}) == []
    assert any(
        "video_serving['identity'] missing" in v
        for v in bench.validate_detail(
            {"video_serving": {k: v for k, v in good["video_serving"].items() if k != "identity"}}
        )
    )
    mistyped = dict(good["video_serving"], effective_speedup="fast")
    assert any(
        "video_serving['effective_speedup']" in v
        for v in bench.validate_detail({"video_serving": mistyped})
    )
    summary = bench.compact_summary({"detail": good})
    assert "video_serving" in summary["sections"]


def test_committed_r19_artifact_video_serving_contract():
    """The round-19 acceptance pin: the committed CPU-smoke artifact ran
    every section (skipped == []), its cached-vs-stateless byte-identity
    audit is green including across the mid-sequence hot swap, the
    effective throughput model clears the >= 3x target at >= 90% overlap,
    the serve_stream_* metrics reached the exposition, and the
    StreamPredict gRPC smoke dropped nothing with the wire audit green."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench_runs", "r19_video_serving_cpu_smoke.json")
    with open(path) as f:
        art = json.load(f)
    assert art["detail"]["skipped"] == []
    video = art["detail"]["video_serving"]
    assert "error" not in video
    assert video["frame"]["overlap_fraction"] >= 0.9
    assert video["effective_speedup"] >= 3.0
    assert video["speedup_target_met"] is True
    assert video["effective_img_per_s"] > video["stateless"]["img_per_s"]
    identity = video["identity"]
    assert identity["ok"] and identity["mismatches"] == 0
    assert identity["frames_checked"] == video["frame"]["frames"]
    swap = video["swap"]
    assert swap["identity_after_swap"] and swap["full_rerun_on_swap"]
    assert swap["stale_entries_purged"] > 0
    assert video["metrics_in_exposition"] is True
    smoke = video["grpc_smoke"]
    assert "error" not in smoke
    assert smoke["frames_dropped"] == 0 and smoke["stills_dropped"] == 0
    assert smoke["audit"]["ok"] and smoke["audit"]["checked"] > 0


def test_lowp_kernels_schema_guard():
    """Round-20 lowp_kernels arm: declared in DETAIL_SCHEMA, its keys
    written by bench.py, typed checks enforced, error-arm exempt, malformed
    per-impl points reported — never a TypeError (the r12 wire-map
    contract)."""
    bench = _import_bench()
    assert "lowp_kernels" in bench.DETAIL_SCHEMA
    assert {"impls", "speedup_vs_reference", "interpret_mode"} <= set(
        bench.LOWP_KERNELS_SCHEMA
    )
    assert {"parity_max_abs_diff", "gate"} <= set(bench.LOWP_IMPL_SCHEMA)
    with open(bench.__file__) as f:
        src = f.read()
    for key in set(bench.LOWP_KERNELS_SCHEMA) | set(bench.LOWP_IMPL_SCHEMA):
        assert f'"{key}"' in src, f"schema key {key!r} never written by bench.py"
    impl = {
        "round_s_short": 0.1,
        "round_s_long": 0.4,
        "per_step_ms": 10.0,
        "mfu": 0.01,
        "parity_max_abs_diff": 1e-6,
        "gate": {"passed": True},
    }
    good = {
        "lowp_kernels": {
            "img": 64,
            "interpret_mode": True,
            "fp8_supported": True,
            "flops_per_forward_canonical": 1e9,
            "impls": {"reference": impl, "fused_int8": impl},
            "speedup_vs_reference": {"fused_int8": 0.5},
        }
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({"lowp_kernels": {"error": "boom"}}) == []
    empty = dict(good["lowp_kernels"], impls={})
    assert any(
        "impls" in v for v in bench.validate_detail({"lowp_kernels": empty})
    )
    broken = dict(
        good["lowp_kernels"],
        impls={"reference": impl, "fused_int8": {"gate": "nope"}},
    )
    bad = bench.validate_detail({"lowp_kernels": broken})
    assert bad and all(isinstance(v, str) for v in bad)


def test_committed_r20_artifact_lowp_kernels_contract():
    """The round-20 acceptance pin: the committed CPU-smoke artifact ran
    every section (skipped == []), both the reference and fused_int8 arms
    were priced, the fused arm's interpret-mode twin matched the reference
    program (tiny parity) and cleared the install gate, and the fp8 arm —
    present exactly when the backend has fp8 dtypes — carries an honest
    gate record either way (its pass/fail is a model-quality fact of the
    tiny smoke model, not pinned here)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench_runs", "r20_lowp_kernels_cpu_smoke.json")
    with open(path) as f:
        art = json.load(f)
    assert art["detail"]["skipped"] == []
    lowp = art["detail"]["lowp_kernels"]
    assert "error" not in lowp
    impls = lowp["impls"]
    assert {"reference", "fused_int8"} <= set(impls)
    assert lowp["interpret_mode"] is True  # a CPU smoke runs the interpreter
    assert impls["reference"]["parity_max_abs_diff"] == 0.0
    fused = impls["fused_int8"]
    assert fused["parity_max_abs_diff"] < 1e-3
    assert fused["gate"]["passed"] is True
    assert fused["effective_kernel_plane"] == "fused_int8"
    assert ("fp8" in impls) == lowp["fp8_supported"]
    if "fp8" in impls:
        gate = impls["fp8"]["gate"]
        assert isinstance(gate["passed"], bool) and 0.0 <= gate["iou"] <= 1.0
    assert set(lowp["speedup_vs_reference"]) == set(impls) - {"reference"}
    assert lowp["flops_per_forward_canonical"] > 0


def test_committed_r21_artifact_robust_aggregation_contract():
    """The round-21 acceptance pin: the committed CPU-smoke artifact ran
    every section (skipped == []), the 4-arm A/B shows the FedAvg arm
    cliffing where every robust/quarantine arm holds the canary at >= 0.9
    with drag cut >= 10x, the quarantine arm's exclusion is visible end to
    end (history map -> ledger count -> health-report join) with the
    poisoned sender NOT_WAIT-resynced, and the colluding-minority variant
    is beaten by every robust arm at n >= 2f+3."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench_runs", "r21_robust_aggregation_cpu_smoke.json")
    with open(path) as f:
        art = json.load(f)
    assert art["detail"]["skipped"] == []
    ra = art["detail"]["robust_aggregation"]
    assert "error" not in ra
    arms = ra["arms"]
    assert {"fedavg", "trimmed_mean", "krum", "fedavg_quarantine"} <= set(arms)
    assert ra["fedavg_cliffed"] and arms["fedavg"]["canary_iou"] < 0.9
    assert arms["fedavg"]["drag"] > 100.0  # the x1000 poison lands in full
    for name in ("trimmed_mean", "krum", "fedavg_quarantine"):
        arm = arms[name]
        assert arm["canary_iou"] >= 0.9, name
        assert arm["drag_reduction_vs_fedavg"] >= 10.0, name
    assert ra["robust_arms_hold"] and ra["drag_reduced_10x"]
    quar = arms["fedavg_quarantine"]
    assert quar["quarantined"] and quar["poisoned_resynced_not_wait"]
    assert quar["ledger_quarantined_count"] >= 1
    assert quar["honest_not_quarantined"] and quar["clean_global_attached"]
    coll = ra["colluding"]
    assert len(coll["colluders"]) * 2 + 3 <= coll["n_clients"]
    assert all(coll["colluders_beaten"].values())
    health = ra["health_report"]
    assert health["schema_violations"] == [] and health["exclusion_visible"]
    assert set(coll["colluders"]) <= set(health["quarantined_clients"])


def test_elastic_fleet_schema_guard():
    """Round-22 elastic-fleet section: error-arm exempt, a present section
    fully typed per arm (mistypes reported, never crashed), the shadow
    block required, and the compact summary lists the section."""
    bench = _import_bench()
    arm = {
        "replicas_band": [1, 3],
        "completed": 120,
        "shed": 0,
        "dropped": 0,
        "p95_ms": 233.1,
        "wall_s": 8.8,
        "replica_seconds": 13.9,
        "replicas_min": 1,
        "replicas_max": 3,
        "replicas_varied": True,
    }
    good = {
        "elastic_fleet": {
            "profile": "diurnal",
            "rate_rps": 24.0,
            "requests": 120,
            "slo_p95_ms": 1500.0,
            "queue_bound": 10,
            "arms": {
                "static_max": dict(arm, replicas_band=[3, 3], replicas_varied=False),
                "static_min": dict(arm, replicas_band=[1, 1], shed=8, replicas_varied=False),
                "autoscaled": arm,
            },
            "autoscaler": {"scale_ups": 2, "scale_downs": 2},
            "autoscaled_cheaper_than_static_max": True,
            "autoscaled_held_slo": True,
            "static_min_shed": True,
            "shadow": {
                "promote": {"verdict": "promote"},
                "rollback": {"verdict": "rollback"},
                "promoted": True,
                "rolled_back": True,
            },
        }
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({"elastic_fleet": {"error": "boom"}}) == []
    assert any(
        "elastic_fleet['shadow'] missing" in v
        for v in bench.validate_detail(
            {"elastic_fleet": {k: v for k, v in good["elastic_fleet"].items() if k != "shadow"}}
        )
    )
    noarms = dict(good["elastic_fleet"], arms={})
    assert any(
        "elastic_fleet['arms'] is empty" in v
        for v in bench.validate_detail({"elastic_fleet": noarms})
    )
    mistyped = dict(
        good["elastic_fleet"],
        arms=dict(good["elastic_fleet"]["arms"], autoscaled=dict(arm, shed="none")),
    )
    assert any(
        "elastic_fleet.arms['autoscaled']['shed']" in v
        for v in bench.validate_detail({"elastic_fleet": mistyped})
    )
    summary = bench.compact_summary({"detail": good})
    assert "elastic_fleet" in summary["sections"]


def test_committed_r22_artifact_elastic_fleet_contract():
    """The round-22 acceptance pin: the committed CPU-smoke artifact ran
    every section (skipped == []); the 3-arm diurnal A/B shows static-min
    shedding at the peak while the autoscaled arm holds p95 under the SLO
    with shed == 0 and dropped == 0 at STRICTLY lower replica-seconds than
    static-max; the replica gauge provably varied mid-profile; and the
    shadow lane promoted the good candidate and rolled back the degraded
    one with the deciding deltas in the records."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench_runs", "r22_elastic_fleet_cpu_smoke.json")
    with open(path) as f:
        art = json.load(f)
    assert art["detail"]["skipped"] == []
    ef = art["detail"]["elastic_fleet"]
    assert "error" not in ef
    arms = ef["arms"]
    assert {"static_max", "static_min", "autoscaled"} <= set(arms)
    auto, smax, smin = arms["autoscaled"], arms["static_max"], arms["static_min"]
    # Shed stays the loud backstop: the autoscaled arm never needed it.
    assert auto["shed"] == 0 and auto["dropped"] == 0
    assert auto["p95_ms"] <= ef["slo_p95_ms"]
    assert ef["autoscaled_held_slo"] is True
    # The whole point: SLO held at strictly lower replica-seconds.
    assert auto["replica_seconds"] < smax["replica_seconds"]
    assert ef["autoscaled_cheaper_than_static_max"] is True
    # The under-provisioned control arm DID shed (and dropped nothing).
    assert smin["shed"] > 0 and smin["dropped"] == 0
    assert ef["static_min_shed"] is True
    # Wire-level proof the fleet resized mid-profile, from the load_gen
    # sampler polling serve_fleet_replicas over HTTP.
    assert auto["replicas_varied"] is True
    assert auto["replicas_max"] > auto["replicas_min"]
    assert not smax["replicas_varied"] and not smin["replicas_varied"]
    assert ef["autoscaler"]["scale_ups"] >= 1
    # Progressive delivery: one promote, one rollback, deltas recorded.
    shadow = ef["shadow"]
    assert shadow["promoted"] is True and shadow["rolled_back"] is True
    promote = shadow["promote"]
    assert promote["verdict"] == "promote" and promote["installed"]
    assert promote["iou"] >= promote["iou_floor"] and promote["reasons"] == []
    rollback = shadow["rollback"]
    assert rollback["verdict"] == "rollback" and not rollback["installed"]
    assert rollback["reasons"] and rollback["iou"] < rollback["iou_floor"]
    assert rollback["psi_max"] > rollback["psi_ceiling"]


def test_privacy_schema_guard():
    """Round-23 privacy section: error-arm exempt, a present section fully
    typed (dp arms, secagg overhead, drill — mistypes reported, never
    crashed), the off arm's epsilon allowed to be None, and the compact
    summary lists the section."""
    bench = _import_bench()
    arm = {
        "noise_multiplier": 1.1,
        "clip_norm": 1.0,
        "epsilon": 1.129401,
        "val_iou": 0.18,
        "val_loss": 0.7,
        "weight_drift_vs_off": 0.17,
    }
    good = {
        "privacy": {
            "rounds": 2,
            "dp_utility": {
                "off": dict(arm, noise_multiplier=0.0, clip_norm=0.0,
                            epsilon=None, weight_drift_vs_off=0.0),
                "sigma_1.1": arm,
            },
            "secagg_overhead": {
                "n_params": 65536,
                "cohort": 3,
                "bits": 24,
                "plaintext_bytes": 262281,
                "masked_bytes": 524416,
                "wire_ratio": 2.0,
                "mask_ms": 1.7,
                "unmask_ms": 1.1,
                "exact_vs_plaintext": True,
            },
            "secagg_drill": {
                "fault_fired": True,
                "dropout_recovered": True,
                "exact_average_bit_for_bit": True,
                "torn_rounds": 0,
            },
            "bench_s": 69.0,
        }
    }
    assert bench.validate_detail(good) == []
    assert bench.validate_detail({"privacy": {"error": "boom"}}) == []
    empty = dict(good["privacy"], dp_utility={})
    assert any(
        "privacy['dp_utility'] is empty" in v
        for v in bench.validate_detail({"privacy": empty})
    )
    mistyped = dict(
        good["privacy"],
        dp_utility=dict(good["privacy"]["dp_utility"],
                        **{"sigma_1.1": dict(arm, epsilon="high")}),
    )
    assert any(
        "privacy.dp_utility['sigma_1.1']" in v
        for v in bench.validate_detail({"privacy": mistyped})
    )
    nodrill = {k: v for k, v in good["privacy"].items() if k != "secagg_drill"}
    assert any(
        "privacy['secagg_drill'] missing" in v
        for v in bench.validate_detail({"privacy": nodrill})
    )
    badbits = dict(
        good["privacy"],
        secagg_overhead=dict(good["privacy"]["secagg_overhead"], bits="24"),
    )
    assert any(
        "privacy.secagg_overhead['bits']" in v
        for v in bench.validate_detail({"privacy": badbits})
    )
    summary = bench.compact_summary({"detail": good})
    assert "privacy" in summary["sections"]


def test_committed_r23_artifact_privacy_contract():
    """The round-23 acceptance pin: the committed CPU-smoke artifact ran
    every section (skipped == []); the DP A/B carries the off arm plus at
    least two noise levels with epsilon DECREASING as sigma rises (the
    accountant's direction) and utility paid for it (drift > 0); the
    secagg masking math is pinned EXACT against the plaintext weighted
    sum; and the real-gRPC dropped-masker drill recovered the pad and
    closed to the survivors' mean bit-for-bit with zero torn rounds."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench_runs", "r23_privacy_cpu_smoke.json")
    with open(path) as f:
        art = json.load(f)
    assert art["detail"]["skipped"] == []
    priv = art["detail"]["privacy"]
    assert "error" not in priv
    arms = priv["dp_utility"]
    assert "off" in arms and len(arms) >= 3
    assert arms["off"]["epsilon"] is None
    assert arms["off"]["weight_drift_vs_off"] == 0.0
    noised = sorted(
        (a for n, a in arms.items() if n != "off"),
        key=lambda a: a["noise_multiplier"],
    )
    for lo, hi in zip(noised, noised[1:]):
        # More noise buys a strictly smaller epsilon at equal rounds.
        assert hi["epsilon"] < lo["epsilon"]
    for a in noised:
        assert a["epsilon"] > 0 and a["clip_norm"] > 0
        assert a["weight_drift_vs_off"] > 0.0  # privacy is not free
        assert 0.0 <= a["val_iou"] <= 1.0
    over = priv["secagg_overhead"]
    assert over["exact_vs_plaintext"] is True
    assert over["masked_bytes"] > over["plaintext_bytes"]
    assert 1.0 < over["wire_ratio"] < 3.0  # uint64 residues vs float32
    drill = priv["secagg_drill"]
    assert drill["fault_fired"] and drill["dropout_recovered"]
    assert drill["exact_average_bit_for_bit"] is True
    assert drill["torn_rounds"] == 0
