"""Round 21: the aggregation algebra and its Byzantine-robust combines.

Three contract families:

1. **Null-instance bitwise pins** — the FedAvg algebra instance must be
   byte-identical to the historical direct ``fedavg`` fold on every plane
   that was rewritten through it (rounds barrier, buffered flush, edge
   partial, mesh ordered fold), including through the FedOpt server step
   (fedadam). "Refactor" means ZERO numeric drift.

2. **Robust combines, closed form** — trimmed-mean / coordinate-median /
   Krum / Multi-Krum against hand-computed 3–5 client cohorts, plus the
   properties that make them safe to deploy: client-reported weights are
   IGNORED (self-reported ``ns`` is attack surface), arrival order never
   changes a byte (canonical tie-breaks), selection returns trees
   VERBATIM.

3. **Ledger-coupled quarantine** — a robust-z-flagged update is excluded
   from the fold (not just flagged), the exclusion is visible in history
   + ledger, and the excluded flush-trigger is resynced with the direct
   ``NOT_WAIT`` + clean-weights reply that fires the client-side EF
   rollback — on both the sync barrier and the buffered flush.
"""

import itertools

import numpy as np
import pytest

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import aggregation as A
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.algorithms import (
    apply_server_opt,
    fedavg,
    make_server_optimizer,
)
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes


def _tree(seed: int):
    rng = np.random.default_rng(seed)
    return {
        "params": {
            "w": rng.normal(size=(3, 4)).astype(np.float32),
            "b": rng.normal(size=(4,)).astype(np.float32),
        },
        "batch_stats": {"m": rng.normal(size=(2,)).astype(np.float32)},
    }


def _flat(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


# ---------- the algebra's null instance: bitwise FedAvg ----------

def test_fedavg_instance_bitwise_matches_primitive():
    trees = [_tree(s) for s in (1, 2, 3)]
    counts = [10, 30, 20]
    triples = list(zip(("a", "b", "c"), counts, trees))
    got = A.fold(A.FedAvg(), triples)
    want = fedavg(trees, counts)
    for g, w in zip(*(t["params"].values() for t in (got, want))):
        np.testing.assert_array_equal(g, w)
    np.testing.assert_array_equal(
        got["batch_stats"]["m"], want["batch_stats"]["m"]
    )


def test_fedavg_instance_zero_weights_degenerates_unweighted():
    # The historical gate: all-zero counts (edge pad cohorts) fall back to
    # the unweighted mean rather than dividing by zero.
    trees = [_tree(s) for s in (4, 5)]
    got = A.fold(A.FedAvg(), [("a", 0, trees[0]), ("b", 0, trees[1])])
    want = fedavg(trees, None)
    np.testing.assert_array_equal(got["params"]["w"], want["params"]["w"])


def test_fold_rejects_empty():
    with pytest.raises(ValueError):
        A.fold(A.FedAvg(), [])


# ---------- robust combines, closed form ----------

def test_trimmed_mean_closed_form():
    trees = [_flat(1.0), _flat(2.0), _flat(1000.0)]
    triples = list(zip("abc", (10, 10, 10), trees))
    got = A.fold(A.TrimmedMean(0.34), triples)  # k = floor(.34*3) = 1
    np.testing.assert_array_equal(got["params"]["w"], _flat(2.0)["params"]["w"])
    # beta=0 trims nothing: the plain unweighted mean.
    got0 = A.fold(A.TrimmedMean(0.0), triples)
    np.testing.assert_allclose(
        got0["params"]["w"], np.full((4, 4), (1.0 + 2.0 + 1000.0) / 3.0)
    )


def test_trimmed_mean_is_per_coordinate():
    # The trimmed tail differs per coordinate: each coordinate drops ITS
    # own extremes, not one global outlier client.
    t1 = {"w": np.array([0.0, 100.0], np.float32)}
    t2 = {"w": np.array([1.0, 1.0], np.float32)}
    t3 = {"w": np.array([100.0, 0.0], np.float32)}
    got = A.fold(A.TrimmedMean(0.34), [("a", 1, t1), ("b", 1, t2), ("c", 1, t3)])
    np.testing.assert_array_equal(got["w"], np.array([1.0, 1.0], np.float32))


def test_coordinate_median_closed_form():
    trees = [_flat(1.0), _flat(2.0), _flat(-1000.0)]
    got = A.fold(A.CoordinateMedian(), list(zip("abc", (1, 1, 1), trees)))
    np.testing.assert_array_equal(got["params"]["w"], _flat(1.0)["params"]["w"])


@pytest.mark.parametrize(
    "make",
    [
        lambda: A.TrimmedMean(0.34),
        lambda: A.CoordinateMedian(),
        lambda: A.Krum(1),
        lambda: A.Krum(1, multi=True),
    ],
)
def test_robust_combines_ignore_reported_weights(make):
    # A Byzantine client's self-reported sample count must buy it nothing.
    trees = [_flat(1.0), _flat(2.0), _flat(1000.0)]
    lo = A.fold(make(), list(zip("abc", (1, 1, 1), trees)))
    hi = A.fold(make(), list(zip("abc", (1, 1, 10**9), trees)))
    np.testing.assert_array_equal(lo["params"]["w"], hi["params"]["w"])


def test_krum_selects_honest_verbatim():
    honest = [_tree(1), _tree(2), _tree(3), _tree(4)]
    poisoned = {
        k: {n: a * 1000.0 for n, a in sub.items()}
        for k, sub in _tree(1).items()
    }
    triples = list(zip("abcde", (1, 1, 1, 1, 1), honest + [poisoned]))
    got = A.fold(A.Krum(1), triples)
    # Krum returns ONE submitted tree verbatim — bitwise, never a blend.
    assert any(
        all(
            np.array_equal(got[k][n], t[k][n])
            for k, sub in t.items()
            for n in sub
        )
        for t in honest
    )
    assert not np.array_equal(got["params"]["w"], poisoned["params"]["w"])


def test_krum_tiebreak_by_name_is_deterministic():
    # Two identical low-score candidates: the lexicographically-first name
    # wins, independent of arrival order.
    t = _flat(1.0)
    far = _flat(500.0)
    for perm in itertools.permutations([("b", 1, t), ("a", 1, t), ("z", 1, far)]):
        got = A.fold(A.Krum(1), list(perm))
        np.testing.assert_array_equal(got["params"]["w"], t["params"]["w"])


def test_multi_krum_closed_form():
    trees = [_flat(1.0), _flat(3.0), _flat(1000.0)]
    got = A.fold(A.Krum(1, multi=True), list(zip("abc", (7, 13, 10**6), trees)))
    # m = n - f = 2 survivors (the honest pair), UNWEIGHTED mean.
    np.testing.assert_allclose(got["params"]["w"], np.full((4, 4), 2.0))


def test_single_update_passthrough_every_combine():
    t = _tree(9)
    for name in A.AGGREGATIONS:
        cfg = _root_cfg(aggregation=name)
        got = A.fold(A.from_config(cfg), [("only", 5, t)])
        if name in ("krum", "multi_krum"):
            # Selection combines return the submitted tree VERBATIM.
            np.testing.assert_array_equal(got["params"]["w"], t["params"]["w"])
        else:
            # Mean-family combines run the arithmetic (weighted divide /
            # f32 stack) even at n=1 — value-identical, not bit-identical.
            np.testing.assert_allclose(
                got["params"]["w"], t["params"]["w"], rtol=1e-6
            )


def test_from_config_dispatch():
    assert isinstance(A.from_config(_root_cfg()), A.FedAvg)
    assert isinstance(
        A.from_config(_root_cfg(aggregation="trimmed_mean")), A.TrimmedMean
    )
    for alias in ("median", "coordinate_median"):
        assert isinstance(
            A.from_config(_root_cfg(aggregation=alias)), A.CoordinateMedian
        )
    krum = A.from_config(_root_cfg(aggregation="krum", byzantine_f=2))
    assert isinstance(krum, A.Krum) and not krum.multi and krum.byzantine_f == 2
    mk = A.from_config(_root_cfg(aggregation="multi_krum"))
    assert isinstance(mk, A.Krum) and mk.multi


# ---------- arrival-order independence for EVERY combine ----------

@pytest.mark.parametrize("aggregation", A.AGGREGATIONS)
def test_rounds_plane_arrival_order_independent(aggregation):
    """Permuted cross-client upload orders produce a BYTE-identical global
    under every combine — the sync barrier sorts by name before the fold,
    and the robust combines' internal orders are canonical."""
    def drive(order):
        cfg = _root_cfg(
            aggregation=aggregation, cohort_size=3, max_rounds=1
        )
        st = R.initial_state(cfg, _flat(0.0))
        now = 0.0
        for c in ("a", "b", "c"):
            now += 1e-3
            st, rep = R.transition(st, R.Ready(cname=c, now=now))
            assert rep.status == R.SW
        values = {"a": 1.0, "b": 1.2, "c": 1.1}
        ns = {"a": 10, "b": 30, "c": 20}
        for c in order:
            now += 1e-3
            st, _ = R.transition(
                st,
                R.TrainDone(
                    cname=c, round=1, blob=tree_to_bytes(_flat(values[c])),
                    num_samples=ns[c], now=now,
                ),
            )
        return st.global_blob

    blobs = {drive(order) for order in itertools.permutations("abc")}
    assert len(blobs) == 1


# ---------- null bitwise pins on the four planes ----------

def test_null_pin_rounds_plane_bitwise():
    cfg = _root_cfg(cohort_size=2, max_rounds=1)
    st = R.initial_state(cfg, _tree(0))
    for i, c in enumerate(("a", "b")):
        st, _ = R.transition(st, R.Ready(cname=c, now=0.1 * (i + 1)))
    st, _ = R.transition(
        st, R.TrainDone(cname="b", round=1, blob=tree_to_bytes(_tree(2)),
                        num_samples=30, now=1.0),
    )
    st, _ = R.transition(
        st, R.TrainDone(cname="a", round=1, blob=tree_to_bytes(_tree(1)),
                        num_samples=10, now=2.0),
    )
    got = tree_from_bytes(st.global_blob)
    # The seed fold: sorted-by-name trees, sample-count weights.
    want = fedavg([_tree(1), _tree(2)], [10, 30])
    np.testing.assert_array_equal(got["params"]["w"], want["params"]["w"])
    np.testing.assert_array_equal(got["params"]["b"], want["params"]["b"])


def test_null_pin_fedadam_sync_bitwise():
    """The algebra feeds the FedOpt server step unchanged: a fedadam round
    lands bit-identical to fedavg + apply_server_opt computed by hand."""
    cfg = _root_cfg(
        cohort_size=2, max_rounds=1, server_optimizer="fedadam",
        server_lr=0.1, server_momentum=0.9,
    )
    base = _tree(0)
    st = R.initial_state(cfg, base)
    for i, c in enumerate(("a", "b")):
        st, _ = R.transition(st, R.Ready(cname=c, now=0.1 * (i + 1)))
    for c, seed, ns in (("a", 1, 10), ("b", 2, 30)):
        st, _ = R.transition(
            st, R.TrainDone(cname=c, round=1, blob=tree_to_bytes(_tree(seed)),
                            num_samples=ns, now=1.0),
        )
    got = tree_from_bytes(st.global_blob)
    avg = fedavg([_tree(1), _tree(2)], [10, 30])
    tx = make_server_optimizer("fedadam", 0.1, 0.9)
    base_rt = tree_from_bytes(tree_to_bytes(base))  # the wire round-trip
    want, _ = apply_server_opt(
        base_rt["params"], avg["params"], tx, tx.init(base_rt["params"])
    )
    np.testing.assert_array_equal(got["params"]["w"], want["w"])
    # BN stats bypass the optimizer: plain average.
    np.testing.assert_array_equal(got["batch_stats"]["m"], avg["batch_stats"]["m"])


def test_null_pin_buffered_plane_bitwise():
    from fedcrack_tpu.fed.buffered import fold_buffer, staleness_weight

    buffer = tuple(
        {"cname": c, "seq": i, "blob": tree_to_bytes(_tree(s)), "ns": ns,
         "staleness": stale, "weight": staleness_weight(stale, 0.5)}
        for i, (c, s, ns, stale) in enumerate(
            (("b", 2, 30, 1), ("a", 1, 10, 0), ("c", 3, 20, 2))
        )
    )
    avg, entries, counts, eff, trees = fold_buffer(buffer, _tree(0))
    order = sorted(buffer, key=lambda e: (e["cname"], e["seq"]))
    want = fedavg(
        [tree_from_bytes(e["blob"], template=_tree(0)) for e in order],
        [e["ns"] * e["weight"] for e in order],
    )
    np.testing.assert_array_equal(avg["params"]["w"], want["params"]["w"])


def test_null_pin_edge_partial_bitwise():
    from fedcrack_tpu.fed.tree import EdgeAggregator

    edge = EdgeAggregator("e0", _tree(0))
    edge.begin_round(1, tree_to_bytes(_tree(0)), 0, ["a", "b"])
    for c, seed, ns in (("b", 2, 30), ("a", 1, 10)):
        edge.offer(c, tree_to_bytes(_tree(seed)), ns)
    blob, total = edge.partial()
    want = fedavg([_tree(1), _tree(2)], [10, 30])  # sorted by name
    got = tree_from_bytes(blob, template=_tree(0))
    np.testing.assert_array_equal(got["params"]["w"], want["params"]["w"])
    assert total == 40


def test_edge_refuses_robust_combines():
    from fedcrack_tpu.fed.tree import EdgeAggregator

    for name in ("trimmed_mean", "median", "krum", "multi_krum"):
        with pytest.raises(ValueError, match="edge tier only supports"):
            EdgeAggregator("e0", _tree(0), aggregation=name)


def test_null_pin_mesh_fold_is_the_algebra():
    """The mesh plane's historical names ARE the algebra's mesh instance —
    alias identity keeps every traced program (and the r13 groups-bitwise
    pins that run over them) byte-for-byte unchanged."""
    from fedcrack_tpu.parallel import fedavg_mesh as M

    assert M._ordered_cohort_sums is A.mesh_ordered_fold
    assert M._zero_sums_like is A.mesh_zero_sums
    assert M._finish_cohort_mean is A.mesh_finish_cohort_mean


# ---------- ledger-coupled quarantine ----------

def _root_cfg(**kw):
    base = dict(cohort_size=3, max_rounds=2, registration_window_s=3600.0)
    base.update(kw)
    return FedConfig(**base)


def test_quarantine_excludes_flagged_update_sync():
    cfg = _root_cfg(cohort_size=3, max_rounds=1, quarantine_z=3.5)
    st = R.initial_state(cfg, _flat(0.0))
    now = 0.0
    for c in ("a", "b", "c"):
        now += 1e-3
        st, _ = R.transition(st, R.Ready(cname=c, now=now))
    for c, v, ns in (("a", 1.0, 10), ("b", 1.2, 10)):
        now += 1e-3
        st, rep = R.transition(
            st, R.TrainDone(cname=c, round=1, blob=tree_to_bytes(_flat(v)),
                            num_samples=ns, now=now),
        )
        assert rep.status == R.RESP_ACY
    # The poisoned update closes the barrier -> it is excluded from the
    # fold it triggered and resynced NOT_WAIT with the CLEAN global (the
    # direct reply that fires the client-side EF rollback, not an RESP_ARY
    # claiming its update was averaged).
    st, rep = R.transition(
        st, R.TrainDone(cname="c", round=1, blob=tree_to_bytes(_flat(1100.0)),
                        num_samples=10, now=now + 1e-3),
    )
    assert rep.status == R.NOT_WAIT
    assert rep.blob  # clean weights attached for the resync
    got = tree_from_bytes(st.global_blob)
    np.testing.assert_allclose(got["params"]["w"], np.full((4, 4), 1.1))
    entry = st.history[0]
    assert list(entry["quarantined"]) == ["c"]
    assert entry["quarantined"]["c"] >= 3.5
    assert entry["clients"] == ["a", "b", "c"]  # who REPORTED, unchanged
    assert st.ledger["c"]["quarantined"] == 1
    assert st.ledger["a"]["quarantined"] == 0


def test_quarantine_never_empties_the_cohort():
    # If the gate would exclude EVERYONE, it excludes no one: a duel of
    # two scaled updates must not zero out the round.
    scores = {"a": 10.0, "b": 12.0}
    assert A.quarantine_set(scores, ["a", "b"], 3.5) == {}
    assert A.quarantine_set(scores, ["a", "b"], 0.0) == {}  # z<=0 disables
    assert A.quarantine_set({"a": 0.1, "b": 9.0}, ["a", "b"], 3.5) == {"b": 9.0}


def test_quarantine_excludes_flagged_update_buffered():
    cfg = FedConfig(
        cohort_size=3, max_rounds=2, registration_window_s=3600.0,
        mode="buffered", buffer_k=3, staleness_alpha=0.0, max_staleness=4,
        quarantine_z=3.5,
    )
    st = R.initial_state(cfg, _flat(0.0))
    now = 0.0
    for c in ("a", "b", "c"):
        now += 1e-3
        st, _ = R.transition(st, R.Ready(cname=c, now=now))
    for c in ("a", "b", "c"):
        now += 1e-3
        st, rep = R.transition(st, R.PullWeights(cname=c, now=now))
        assert rep.status == "OK"
    for c, v in (("a", 1.0), ("b", 1.2)):
        now += 1e-3
        st, rep = R.transition(
            st, R.TrainDone(cname=c, round=1, blob=tree_to_bytes(_flat(v)),
                            num_samples=10, now=now),
        )
        assert rep.status == R.RESP_ACY
    st, rep = R.transition(
        st, R.TrainDone(cname="c", round=1, blob=tree_to_bytes(_flat(1100.0)),
                        num_samples=10, now=now + 1e-3),
    )
    assert rep.status == R.NOT_WAIT and rep.blob
    got = tree_from_bytes(st.global_blob)
    np.testing.assert_allclose(got["params"]["w"], np.full((4, 4), 1.1))
    assert list(st.history[-1]["quarantined"]) == ["c"]
    assert st.ledger["c"]["quarantined"] == 1


def test_quarantine_zero_z_is_the_seed_behavior():
    # quarantine_z=0 (the default): nothing excluded even at huge z.
    cfg = _root_cfg(cohort_size=2, max_rounds=1)
    st = R.initial_state(cfg, _flat(0.0))
    for i, c in enumerate(("a", "b")):
        st, _ = R.transition(st, R.Ready(cname=c, now=0.1 * (i + 1)))
    st, _ = R.transition(
        st, R.TrainDone(cname="a", round=1, blob=tree_to_bytes(_flat(1.0)),
                        num_samples=10, now=1.0),
    )
    st, rep = R.transition(
        st, R.TrainDone(cname="b", round=1, blob=tree_to_bytes(_flat(1000.0)),
                        num_samples=10, now=2.0),
    )
    assert rep.status in (R.RESP_ARY, R.FIN)
    assert st.history[0]["quarantined"] == {}


# ---------- ledger wire compat (13 -> 14 fields) ----------

def test_ledger_wire_roundtrips_quarantined_and_reads_old_rows():
    from fedcrack_tpu.health import ledger as L

    led = {"a": L.new_record()}
    led = L.record_quarantine(led, "a")
    rows = L.ledger_to_wire(led)
    back = L.ledger_from_wire(rows)
    assert back["a"]["quarantined"] == 1
    # A pre-r21 13-field row restores with the counter defaulted to 0.
    old = [list(r)[:13] for r in rows]
    back_old = L.ledger_from_wire(old)
    assert back_old["a"]["quarantined"] == 0
    assert back_old["a"]["offers"] == back["a"]["offers"]


# ---------- config validation + round-trip ----------

def test_config_validates_aggregation_fields():
    with pytest.raises(ValueError, match="aggregation"):
        FedConfig(aggregation="geometric_median")
    with pytest.raises(ValueError, match="trim_fraction"):
        FedConfig(trim_fraction=0.5)
    with pytest.raises(ValueError, match="trim_fraction"):
        FedConfig(trim_fraction=-0.1)
    with pytest.raises(ValueError, match="byzantine_f"):
        FedConfig(byzantine_f=-1)
    with pytest.raises(ValueError, match="quarantine_z"):
        FedConfig(quarantine_z=-0.5)


def test_config_roundtrips_aggregation_fields():
    cfg = FedConfig(
        aggregation="multi_krum", trim_fraction=0.2, byzantine_f=2,
        quarantine_z=3.5,
    )
    back = FedConfig.from_json(cfg.to_json())
    assert back.aggregation == "multi_krum"
    assert back.trim_fraction == 0.2
    assert back.byzantine_f == 2
    assert back.quarantine_z == 3.5
    assert back == cfg
