"""Serve fleet + quantized predict (round 17): quant A/B gate, fleet-wide
two-phase hot swap, router admission control, replica crash failover, load
profiles, and the persistent-compile-cache warm boot.

The load-bearing claims, each pinned here:

- int8 weight quantization is deterministic (same weights -> byte-identical
  codes/scales) with per-entry error bounded by scale/2, and the install
  gate REFUSES a quantized build whose probe mask IoU falls below the floor
  — the fleet keeps serving the reference program (bf16 fallback), outputs
  bit-equal to a never-quantized fleet;
- the fleet swap is torn-version-free: after ``install`` returns, every
  request on every replica answers from the new version, and a batch that
  snapshotted before the commit answers entirely from its snapshot (the
  straddle contract);
- admission control sheds loudly (LoadShedError / RESOURCE_EXHAUSTED over
  gRPC) on queue bound and rolling-p95 breach, and NEVER sheds an already
  accepted request;
- a killed replica's queued requests reroute to survivors with their
  original futures — zero accepted requests dropped, swap still lands;
- a second engine build against the same persistent compilation cache adds
  zero new cache entries (the warm-boot claim).
"""

import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serve

TINY_KW = dict(
    img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
BUCKETS = (16, 32)


def _serve_config(**over):
    from fedcrack_tpu.configs import ServeConfig

    kw = dict(
        bucket_sizes=BUCKETS, max_batch=4, max_delay_ms=10.0, tile_overlap=4
    )
    kw.update(over)
    return ServeConfig(**kw)


@pytest.fixture(scope="module")
def stack():
    """Shared compiled engines (reference + int8) and two weight versions —
    the bucket compiles dominate test cost; every test takes fresh fleets
    over the same engines."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import InferenceEngine

    model_config = ModelConfig(**TINY_KW)
    engine_ref = InferenceEngine(model_config, _serve_config())
    engine_q = InferenceEngine(model_config, _serve_config(quant="int8"))
    var0 = init_variables(jax.random.key(0), model_config)
    var1 = init_variables(jax.random.key(1), model_config)
    return model_config, engine_ref, engine_q, var0, var1


def _fleet(stack, *, quant="none", replicas=2, chaos=None, **cfg_over):
    from fedcrack_tpu.serve import ServeFleet

    model_config, engine_ref, engine_q, var0, _ = stack
    cfg = _serve_config(quant=quant, replicas=replicas, **cfg_over)
    return ServeFleet(
        model_config,
        cfg,
        var0,
        shared_engine=engine_q if quant == "int8" else engine_ref,
        chaos=chaos,
        warmup=False,
    )


def _img(size, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (size, size, 3), dtype=np.uint8)


# ---- quantization units ----


def test_quantize_leaf_deterministic_and_bounded():
    from fedcrack_tpu.serve.quant import QKEY, SKEY, quantize_leaf

    rng = np.random.default_rng(3)
    w = rng.normal(0, 0.1, (3, 3, 8, 16)).astype(np.float32)
    a, b = quantize_leaf(w), quantize_leaf(w)
    assert np.array_equal(a[QKEY], b[QKEY]) and np.array_equal(a[SKEY], b[SKEY])
    assert a[QKEY].dtype == np.int8 and a[SKEY].shape == (16,)
    # Per-entry dequantization error <= half a quantization step.
    deq = a[QKEY].astype(np.float32) * a[SKEY]
    assert np.all(np.abs(deq - w) <= a[SKEY] / 2 + 1e-9)


def test_quantize_leaf_zero_channel_is_exact():
    from fedcrack_tpu.serve.quant import QKEY, SKEY, quantize_leaf

    w = np.zeros((3, 3, 2, 4), np.float32)
    w[..., 1] = 0.5  # one live channel among dead ones
    q = quantize_leaf(w)
    assert np.all(q[SKEY][[0, 2, 3]] == 1.0)  # dead channels: scale 1, code 0
    deq = q[QKEY].astype(np.float32) * q[SKEY]
    assert np.array_equal(deq[..., 0], w[..., 0])


def test_quantize_variables_selects_kernels_only(stack):
    import jax

    from fedcrack_tpu.serve.quant import quantize_variables

    _, _, _, var0, _ = stack
    q = quantize_variables(var0)
    # batch_stats stay raw float arrays; params kernels become q-leaves.
    flat_ref = jax.tree_util.tree_leaves(var0)
    flat_q = jax.tree_util.tree_leaves(q.tree)
    assert any(leaf.dtype == np.int8 for leaf in flat_q)
    n_kernels = sum(1 for leaf in flat_ref if leaf.ndim >= 2)
    assert sum(1 for leaf in flat_q if leaf.dtype == np.int8) == n_kernels
    from fedcrack_tpu.serve.quant import quantized_bytes

    q_bytes, ref_bytes = quantized_bytes(q.tree)
    assert q_bytes < ref_bytes / 2  # int8 kernels dominate the tree


def test_mask_iou_units():
    from fedcrack_tpu.serve.quant import mask_iou

    a = np.zeros((4, 4, 1), np.float32)
    b = np.zeros((4, 4, 1), np.float32)
    assert mask_iou(a, b) == 1.0  # both empty = agreement
    a[0, 0] = 1.0
    assert mask_iou(a, b) == 0.0
    b[0, 0] = 1.0
    assert mask_iou(a, b) == 1.0
    b[1, 1] = 1.0
    assert mask_iou(a, b) == pytest.approx(0.5)


# ---- the A/B gate ----


def test_quant_gate_passes_on_tiny_model(stack):
    from fedcrack_tpu.serve.quant import quant_gate, quantize_variables

    _, _, engine_q, var0, _ = stack
    ref = engine_q.prepare(var0)
    qv = engine_q.prepare_quantized(quantize_variables(var0))
    gate = quant_gate(engine_q, ref, qv, floor=0.5)
    assert gate.passed and 0.5 <= gate.iou <= 1.0
    assert set(gate.per_bucket) == set(BUCKETS)
    # Deterministic: the same gate re-run returns the same IoU.
    gate2 = quant_gate(engine_q, ref, qv, floor=0.5)
    assert gate2.iou == gate.iou


def test_quant_gate_failure_refuses_and_serves_bf16(stack, monkeypatch):
    """A garbage quantized build (codes zeroed) must fail the gate; the
    fleet REFUSES it and serves the reference program — outputs equal a
    never-quantized fleet's, and the refusal is recorded loudly."""
    from fedcrack_tpu.serve import quant as quant_mod

    real_quantize = quant_mod.quantize_variables

    def garbage_quantize(variables):
        q = real_quantize(variables)

        def zero(node):
            if isinstance(node, dict) and set(node) == {quant_mod.QKEY, quant_mod.SKEY}:
                return {
                    quant_mod.QKEY: np.zeros_like(node[quant_mod.QKEY]),
                    quant_mod.SKEY: node[quant_mod.SKEY],
                }
            if isinstance(node, dict):
                return {k: zero(v) for k, v in node.items()}
            return node

        return quant_mod.QuantizedVariables(zero(q.tree))

    monkeypatch.setattr(
        "fedcrack_tpu.serve.quant.quantize_variables", garbage_quantize
    )
    fleet = _fleet(stack, quant="int8")
    try:
        gate = fleet.manager.last_quant_gate
        assert gate is not None and gate["passed"] is False
        # bf16 fallback: the served payload is NOT a quantized wrapper...
        from fedcrack_tpu.serve.quant import QuantizedVariables

        _, payload = fleet.manager.snapshot_for(0)
        assert not isinstance(payload, QuantizedVariables)
        # ...and answers match the reference program bit-for-bit.
        img = _img(16)
        got = fleet.submit(img).result(timeout=60)
        _, _, engine_q, var0, _ = stack
        want = engine_q.predict_bucket(engine_q.prepare(var0), img[None])
        np.testing.assert_array_equal(got.probs, want[0])
    finally:
        fleet.close()


def test_quant_gate_pass_serves_quantized(stack):
    fleet = _fleet(stack, quant="int8")
    try:
        gate = fleet.manager.last_quant_gate
        assert gate is not None
        from fedcrack_tpu.serve.quant import QuantizedVariables

        _, payload = fleet.manager.snapshot_for(0)
        if gate["passed"]:
            assert isinstance(payload, QuantizedVariables)
        else:  # honest refuse on this seed: fallback contract instead
            assert not isinstance(payload, QuantizedVariables)
        # Either way requests answer.
        res = fleet.submit(_img(16)).result(timeout=60)
        assert res.probs.shape == (16, 16, 1)
        # The IoU gauge carries the measured ratio.
        from fedcrack_tpu.obs.registry import REGISTRY

        g = REGISTRY.gauge("serve_quant_iou_ratio", "")
        assert g.value == pytest.approx(gate["iou"], abs=1e-6)
    finally:
        fleet.close()


def test_quantized_predict_deterministic(stack):
    """Two runs of the quantized program on the same inputs are
    byte-identical (the serve plane's determinism discipline survives
    quantization)."""
    from fedcrack_tpu.serve.quant import quantize_variables

    _, _, engine_q, var0, _ = stack
    qv = engine_q.prepare_quantized(quantize_variables(var0))
    batch = np.stack([_img(32, seed=i) for i in range(3)])
    a = engine_q.predict_bucket(qv, batch)
    b = engine_q.predict_bucket(qv, batch)
    np.testing.assert_array_equal(a, b)


# ---- fleet two-phase swap ----


def test_fleet_swap_zero_torn_versions(stack):
    """After install() returns, every request on every replica answers v1;
    pre-install responses were all v0. The commit barrier, measured."""
    _, _, _, _, var1 = stack
    fleet = _fleet(stack, replicas=3)
    try:
        img = _img(16)
        pre = [fleet.submit(img) for _ in range(9)]
        pre_versions = {f.result(timeout=60).model_version for f in pre}
        assert pre_versions == {0}
        assert fleet.install(1, var1)
        post = [fleet.submit(img) for _ in range(9)]
        post_versions = {f.result(timeout=60).model_version for f in post}
        assert post_versions == {1}, f"torn versions: {post_versions}"
        assert fleet.manager.last_swap["pause_ms"] is not None
        # Re-installing an older or equal version is a no-op.
        assert not fleet.install(1, var1)
        assert not fleet.install(0, var1)
    finally:
        fleet.close()


def test_fleet_swap_straddling_batch_answers_from_snapshot(stack):
    """A batch whose snapshot was taken BEFORE the commit must answer from
    that snapshot even though the fleet-wide flip lands while it is in
    flight — the r10 torn-read barrier, fleet edition. The chaos hook runs
    between snapshot and dispatch: exactly the straddle window."""
    _, _, _, var0, var1 = stack
    fired = {"done": False}
    holder = {}

    class SwapMidBatch:
        def on_batch(self, bucket, batch_index, attempt):
            if not fired["done"] and holder.get("fleet") is not None:
                fired["done"] = True
                assert holder["fleet"].install(1, var1)

    fleet = _fleet(stack, replicas=2, chaos=SwapMidBatch())
    holder["fleet"] = fleet
    try:
        res = fleet.submit(_img(16)).result(timeout=60)
        assert fired["done"]
        # Snapshot was v0; the fleet is ALREADY v1 when the answer lands.
        assert res.model_version == 0
        assert fleet.manager.version == 1
        after = fleet.submit(_img(16)).result(timeout=60)
        assert after.model_version == 1
    finally:
        fleet.close()


def test_fleet_poll_installs_from_statefile(stack, tmp_path):
    """The fleet manager watches the same federation outputs as the r10
    manager (shared WeightSourceWatcher): a published statefile swaps every
    replica."""
    from fedcrack_tpu.serve import ServeFleet
    from fedcrack_tpu.serve.hot_swap import publish_statefile

    model_config, engine_ref, _, var0, var1 = stack
    state = tmp_path / "state.msgpack"
    fleet = ServeFleet(
        model_config,
        _serve_config(replicas=2),
        var0,
        shared_engine=engine_ref,
        state_path=str(state),
        template=var0,
        warmup=False,
    )
    try:
        assert not fleet.manager.poll_once()  # nothing published yet
        publish_statefile(str(state), var1, model_version=7)
        assert fleet.manager.poll_once()
        assert fleet.manager.version == 7
        for i in range(2):
            v, _ = fleet.manager.snapshot_for(i)
            assert v == 7
    finally:
        fleet.close()


# ---- router: dispatch + admission control ----


def test_router_least_outstanding_deterministic(stack):
    fleet = _fleet(stack, replicas=3)
    try:
        router = fleet.router
        # Idle fleet: ties break to the lowest index.
        assert router._pick(16).index == 0
        futs = [fleet.submit(_img(16)) for _ in range(6)]
        [f.result(timeout=60) for f in futs]
        counts = [r.batcher.stats()["completed"] for r in fleet.replicas]
        assert sum(counts) == 6
        assert all(c > 0 for c in counts)  # load spread, not pinned to one
    finally:
        fleet.close()


def test_router_sheds_on_queue_bound(stack):
    """With queues artificially backed up past queue_bound, the next submit
    raises LoadShedError(queue_bound) — and metric + counter agree."""
    from fedcrack_tpu.obs.registry import REGISTRY
    from fedcrack_tpu.serve.router import SHED_QUEUE_BOUND, LoadShedError

    class SlowBatches:
        def on_batch(self, bucket, batch_index, attempt):
            time.sleep(0.15)

    fleet = _fleet(stack, replicas=2, chaos=SlowBatches(), queue_bound=2)
    try:
        m = REGISTRY.counter("serve_shed_total", "", labels=("reason",))
        before = m.labels(reason=SHED_QUEUE_BOUND).value
        accepted = []
        shed = 0
        for _ in range(24):
            try:
                accepted.append(fleet.submit(_img(16)))
            except LoadShedError as e:
                assert e.reason == SHED_QUEUE_BOUND
                shed += 1
        assert shed > 0, "queue bound never tripped"
        # Every ACCEPTED request still answers — shedding is accept-time only.
        for f in accepted:
            assert f.result(timeout=60).probs.shape == (16, 16, 1)
        assert fleet.router.shed_counts()[SHED_QUEUE_BOUND] == shed
        assert m.labels(reason=SHED_QUEUE_BOUND).value == before + shed
    finally:
        fleet.close()


def test_router_sheds_on_p95_slo(stack):
    from fedcrack_tpu.serve.router import (
        MIN_SHED_SAMPLES,
        SHED_P95_SLO,
        LoadShedError,
    )

    fleet = _fleet(stack, replicas=2, slo_p95_ms=50.0)
    try:
        # Below the arming threshold nothing sheds even with slow samples.
        for _ in range(MIN_SHED_SAMPLES - 1):
            fleet.router.rolling.add(500.0)
        fleet.submit(_img(16)).result(timeout=60)
        # Armed + breaching: the next submit sheds with the p95 reason.
        for _ in range(MIN_SHED_SAMPLES):
            fleet.router.rolling.add(500.0)
        with pytest.raises(LoadShedError) as err:
            fleet.submit(_img(16))
        assert err.value.reason == SHED_P95_SLO
    finally:
        fleet.close()


def test_rolling_percentiles_window_forgets():
    from fedcrack_tpu.serve.router import RollingPercentiles

    rp = RollingPercentiles(window_s=0.05, capacity=128)
    for _ in range(32):
        rp.add(1000.0)
    assert rp.percentile(95.0) == pytest.approx(1000.0)
    # Two window rotations later the breach has aged out entirely.
    time.sleep(0.12)
    rp.add(1.0)  # rotation happens on access
    time.sleep(0.12)
    for _ in range(8):
        rp.add(1.0)
    assert rp.percentile(95.0) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        RollingPercentiles(window_s=0.0)


# ---- replica crash failover ----


def test_replica_crash_reroutes_queued_requests(stack):
    """Kill a replica with a queued backlog: drained requests reroute to
    the survivor with their ORIGINAL futures, zero accepted requests drop,
    and the fleet swap still lands on the survivors."""
    from fedcrack_tpu.chaos.plan import SERVE_REPLICA_CRASH, Fault, FaultPlan

    _, _, _, _, var1 = stack

    class SlowBatches:
        def on_batch(self, bucket, batch_index, attempt):
            time.sleep(0.08)

    plan = FaultPlan([Fault(kind=SERVE_REPLICA_CRASH, round=1)])
    fleet = _fleet(stack, replicas=2, chaos=SlowBatches())
    try:
        img = _img(16)
        futs = [fleet.submit(img) for _ in range(16)]
        assert plan.take(SERVE_REPLICA_CRASH, round=1) is not None
        out = fleet.router.kill_replica(1)
        assert out["failed"] == 0
        results = [f.result(timeout=120) for f in futs]
        assert len(results) == 16  # zero dropped
        assert out["rerouted"] > 0, "kill landed after the queue drained"
        # Dead replica is out of rotation; new traffic still flows.
        assert fleet.router.live_replicas()[0].index == 0
        assert fleet.submit(img).result(timeout=60).probs.shape == (16, 16, 1)
        # The fleet swap still lands on the degraded fleet.
        assert fleet.install(1, var1)
        assert fleet.submit(img).result(timeout=60).model_version == 1
        # Double-kill is a no-op; killing the last replica leaves nothing.
        assert fleet.router.kill_replica(1)["already_dead"] is True
    finally:
        fleet.close()


def test_replica_crash_fault_kind_registered():
    from fedcrack_tpu.chaos.plan import (
        ALL_KINDS,
        FLEET_KINDS,
        SERVE_REPLICA_CRASH,
        Fault,
    )

    assert SERVE_REPLICA_CRASH in FLEET_KINDS and SERVE_REPLICA_CRASH in ALL_KINDS
    Fault(kind=SERVE_REPLICA_CRASH, round=0)  # constructs clean


# ---- gRPC shed e2e ----


def test_grpc_shed_path_e2e(stack):
    """Front-door overload: an open-loop RAMP injects past the (chaos-
    slowed) fleet's service rate over the real socket; admission control
    sheds with RESOURCE_EXHAUSTED, load_gen counts shed apart from drops
    and rejects (per phase), and zero accepted requests drop. Open loop is
    the shape that CAN overload: injection is schedule-driven over parallel
    streams, not completion-paced like the closed loop."""
    from fedcrack_tpu.serve import ServeServer, ServeServerThread, ServeService
    from fedcrack_tpu.tools.load_gen import run_load

    class SlowBatches:
        def on_batch(self, bucket, batch_index, attempt):
            time.sleep(0.25)

    fleet = _fleet(stack, replicas=2, chaos=SlowBatches(), queue_bound=2)
    server = ServeServer(
        ServeService(fleet.engine, fleet.router, fleet.manager), port=0
    )
    try:
        with ServeServerThread(server) as thread:
            summary = run_load(
                f"127.0.0.1:{thread.port}",
                mode="open",
                profile="ramp",
                n_requests=32,
                rate_rps=120.0,
                concurrency=8,
                sizes=(16,),
                seed=0,
                timeout_s=120.0,
            )
    finally:
        fleet.close()
    assert summary["shed"] > 0, "admission control never fired"
    assert summary["dropped"] == 0
    assert summary["rejected"] == 0  # sheds are NOT rejects
    assert summary["completed"] + summary["shed"] == 32
    assert server.service.shed == summary["shed"]
    phases = summary["per_phase"]
    assert [p["phase"] for p in phases] == [
        "ramp_0.25x", "ramp_0.5x", "ramp_1x", "ramp_2x",
    ]
    assert sum(p["shed"] for p in phases) == summary["shed"]
    # The overload lives in the ramp's tail, not its warmup.
    assert sum(p["shed"] for p in phases[2:]) > 0


# ---- load profiles ----


def test_arrival_schedule_const():
    from fedcrack_tpu.tools.load_gen import arrival_schedule

    offsets, phases, meta = arrival_schedule("const", 10, 20.0, seed=3)
    assert offsets == [i * 0.05 for i in range(10)]
    assert phases == [0] * 10
    assert meta[0]["phase"] == "const" and meta[0]["requests"] == 10


def test_arrival_schedule_ramp_seeded_and_shaped():
    from fedcrack_tpu.tools.load_gen import RAMP_PHASES, arrival_schedule

    a = arrival_schedule("ramp", 40, 10.0, seed=7)
    b = arrival_schedule("ramp", 40, 10.0, seed=7)
    assert a == b  # seeded: replayable schedule
    c = arrival_schedule("ramp", 40, 10.0, seed=8)
    assert a[0] != c[0]  # different seed, different gaps
    offsets, phases, meta = a
    assert len(offsets) == 40 and sorted(offsets) == offsets
    assert [m["requests"] for m in meta] == [10, 10, 10, 10]
    rates = [m["target_rps"] for m in meta]
    assert rates == [10.0 * m for _, m in RAMP_PHASES]
    # Phase indices are contiguous and ordered.
    assert phases == sorted(phases) and set(phases) == {0, 1, 2, 3}


def test_arrival_schedule_diurnal_and_validation():
    from fedcrack_tpu.tools.load_gen import DIURNAL_PHASES, arrival_schedule

    offsets, phases, meta = arrival_schedule("diurnal", 21, 5.0, seed=0)
    assert len(offsets) == 21
    assert [m["phase"] for m in meta] == [n for n, _ in DIURNAL_PHASES]
    assert sum(m["requests"] for m in meta) == 21
    with pytest.raises(ValueError):
        arrival_schedule("sawtooth", 10, 5.0)
    with pytest.raises(ValueError):
        arrival_schedule("ramp", 0, 5.0)
    with pytest.raises(ValueError):
        arrival_schedule("ramp", 10, 0.0)


def test_run_load_profile_needs_open_mode():
    from fedcrack_tpu.tools.load_gen import run_load

    with pytest.raises(ValueError):
        run_load("127.0.0.1:1", mode="closed", profile="ramp")


# ---- compile cache warm boot ----


def test_compile_cache_warm_boot(tmp_path):
    """Second engine build against the same persistent cache adds ZERO new
    cache entries — every program is a hit (the replica warm-boot claim;
    cross-process reuse follows because the cache is keyed on the program,
    not the process)."""
    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.jaxcompat import enable_compilation_cache
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve import InferenceEngine

    cache_dir = str(tmp_path / "xla_cache")
    prev = jax.config.jax_compilation_cache_dir
    assert enable_compilation_cache(cache_dir)
    try:
        # A config no other test compiles, so the first build is cold.
        model_config = ModelConfig(
            img_size=16, stem_features=2, encoder_features=(4,),
            decoder_features=(4, 2),
        )
        serve_config = _serve_config(bucket_sizes=(16,), max_batch=2)
        var = init_variables(jax.random.key(0), model_config)

        def cache_entries():
            return sorted(
                f for f in os.listdir(cache_dir) if f.endswith("-cache")
            )

        e1 = InferenceEngine(model_config, serve_config)
        e1.warmup(e1.prepare(var))
        first = cache_entries()
        assert first, "no cache entries written on the cold build"
        t0 = time.perf_counter()
        e2 = InferenceEngine(model_config, serve_config)
        e2.warmup(e2.prepare(var))
        warm_s = time.perf_counter() - t0
        assert cache_entries() == first, "warm build missed the cache"
        assert warm_s < 60.0  # sanity: the warm path must not re-pay compile
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


# ---- config validation ----


def test_serve_config_fleet_validation():
    from fedcrack_tpu.configs import ServeConfig

    _serve_config(replicas=4, quant="int8", slo_p95_ms=100.0, queue_bound=64)
    with pytest.raises(ValueError):
        _serve_config(replicas=0)
    with pytest.raises(ValueError):
        _serve_config(quant="fp8")
    with pytest.raises(ValueError):
        _serve_config(quant_iou_floor=0.0)
    with pytest.raises(ValueError):
        _serve_config(quant_iou_floor=1.5)
    with pytest.raises(ValueError):
        _serve_config(quant_probe_batch=0)
    with pytest.raises(ValueError):
        _serve_config(slo_p95_ms=-1.0)
    with pytest.raises(ValueError):
        _serve_config(queue_bound=-1)
    assert ServeConfig().replicas == 1 and ServeConfig().quant == "none"


def test_c14_preset_round_trips():
    from fedcrack_tpu.configs import FedConfig

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "configs", "c14_serve_fleet.json")) as f:
        fed = FedConfig.from_json(f.read())
    assert fed.serve.replicas == 4
    assert fed.serve.quant == "int8"
    assert fed.serve.queue_bound == 256
    assert fed.serve.slo_p95_ms == 250.0
    assert FedConfig.from_json(fed.to_json()) == fed


# ---- fleet metrics ----


def test_fleet_replicas_gauge_tracks_kills(stack):
    from fedcrack_tpu.obs.registry import REGISTRY

    fleet = _fleet(stack, replicas=3)
    try:
        g = REGISTRY.gauge("serve_fleet_replicas", "")
        assert g.value == 3
        fleet.router.kill_replica(2)
        assert g.value == 2
    finally:
        fleet.close()


def test_fleet_swap_pause_histogram_recorded(stack):
    from fedcrack_tpu.obs.registry import REGISTRY

    _, _, _, _, var1 = stack
    fleet = _fleet(stack, replicas=2)
    try:
        h = REGISTRY.histogram("serve_fleet_swap_pause_seconds", "")
        before = h.snapshot()["count"]
        assert fleet.install(1, var1)
        assert h.snapshot()["count"] == before + 1
    finally:
        fleet.close()
