"""Resident data plane (round 9).

The non-negotiable gate: ``data_placement="resident"`` — the device-resident
``SamplePool`` plus per-round int32 gather plans, with batches assembled
on-device by ``jnp.take`` — must produce a round trajectory BYTE-identical
to the streamed path (weights AND metrics) on the same pool + shuffle rng,
for the monolithic round and for ``segments=10``, while the driver stages
only kilobytes of indices per round (``RoundRecord.staged_bytes``). On top
of that: the s2d pre-packed staging twin, bit-identical chaos replay after
an injected device loss re-stages the pool, and the HBM-guard fallback to
the streamed path.
"""

import json
import os

import jax
import numpy as np
import pytest

from fedcrack_tpu.configs import FedConfig, ModelConfig
from fedcrack_tpu.data.pipeline import (
    SamplePool,
    space_to_depth_images,
    to_uint8_transport,
)
from fedcrack_tpu.data.synthetic import synth_crack_batch
from fedcrack_tpu.parallel import (
    build_federated_round,
    build_federated_round_segments,
    make_mesh,
    resident_pool_fits,
    run_mesh_federation,
)
from fedcrack_tpu.train.local import create_train_state

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)
# EPOCHS=10 so segments=10 exercises the flagship one-segment-per-epoch
# configuration (the acceptance pin is K in {0, 10}); shapes match
# tests/test_segmented.py so the streamed programs hit the persistent
# compilation cache.
STEPS, BATCH, N_CLIENTS, EPOCHS, ROUNDS = 2, 4, 2, 10, 2
POOL_N = STEPS * BATCH + 3  # deduplicated pool strictly larger than a round


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N_CLIENTS, 1)


@pytest.fixture(scope="module")
def pool():
    return SamplePool.stack(
        [
            to_uint8_transport(*synth_crack_batch(POOL_N, TINY.img_size, seed=c))
            for c in range(N_CLIENTS)
        ]
    )


@pytest.fixture(scope="module")
def variables():
    return create_train_state(jax.random.key(0), TINY).variables


ACTIVE = np.ones(N_CLIENTS, np.float32)
N_SAMP = np.full(N_CLIENTS, float(STEPS * BATCH), np.float32)


def _idx_data_fn(pool):
    """Resident-contract data_fn: one fresh permutation per client per
    round, tiled across epochs — the same draw shuffled_epoch_data makes."""
    rngs = [np.random.default_rng(7 + c) for c in range(N_CLIENTS)]

    def data_fn(r):
        return pool.round_indices(rngs, EPOCHS, STEPS, BATCH), ACTIVE, N_SAMP

    return data_fn


def _slab_data_fn(pool):
    """Streamed-contract twin: the SAME rng schedule, slabs host-assembled
    from the same pool — pool[idx] on host is the gather's byte oracle."""
    rngs = [np.random.default_rng(7 + c) for c in range(N_CLIENTS)]

    def data_fn(r):
        idx = pool.round_indices(rngs, EPOCHS, STEPS, BATCH)
        images, masks = pool.assemble_round_slab(idx)
        return images, masks, ACTIVE, N_SAMP

    return data_fn


def _assert_trees_bytes_equal(got, want):
    gl = jax.tree_util.tree_leaves_with_path(got)
    wl = jax.tree_util.tree_leaves(want)
    assert len(gl) == len(wl)
    for (path, g), w in zip(gl, wl):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=jax.tree_util.keystr(path)
        )


@pytest.fixture(scope="module")
def streamed_round(mesh):
    return build_federated_round(mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS)


@pytest.fixture(scope="module")
def resident_round(mesh):
    return build_federated_round(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS,
        data_placement="resident",
    )


@pytest.fixture(scope="module")
def streamed_result(mesh, pool, variables, streamed_round):
    return run_mesh_federation(
        streamed_round, variables, _slab_data_fn(pool), ROUNDS, mesh
    )


@pytest.fixture(scope="module")
def resident_result(mesh, pool, variables, resident_round):
    return run_mesh_federation(
        resident_round,
        variables,
        _idx_data_fn(pool),
        ROUNDS,
        mesh,
        data_placement="resident",
        sample_pool=pool,
    )


def test_resident_monolithic_trajectory_byte_identical(
    streamed_result, resident_result
):
    """Acceptance pin (segments=0): weights AND per-round metrics of the
    resident federation equal the streamed federation byte for byte —
    device gather of identical bytes into the identical sgd_step sequence
    is the identical trajectory."""
    v_s, rec_s = streamed_result
    v_r, rec_r = resident_result
    _assert_trees_bytes_equal(v_r, v_s)
    for rs, rr in zip(rec_s, rec_r):
        for k in rs.metrics:
            np.testing.assert_array_equal(rr.metrics[k], rs.metrics[k], err_msg=k)


def test_resident_staged_bytes_are_indices_only(
    pool, streamed_result, resident_result
):
    """Acceptance pin: per-round driver-staged bytes in resident mode are
    <= 1% of the streamed slab (the gather plan only); the pool is charged
    ONCE to the first record; max_live_staged_bytes carries the resident
    pool for every round."""
    _, rec_s = streamed_result
    _, rec_r = resident_result
    slab_bytes = rec_s[0].staged_bytes
    assert slab_bytes > 0
    assert all(r.data_placement == "resident" for r in rec_r)
    assert all(r.data_placement == "streamed" for r in rec_s)
    # First record: one-time pool transfer + that round's plan.
    idx_bytes = rec_r[1].staged_bytes
    assert idx_bytes == N_CLIENTS * EPOCHS * STEPS * BATCH * 4  # the plan, exactly
    assert rec_r[0].staged_bytes == pool.nbytes + idx_bytes
    # Steady state: EVERY later round stages the plan and nothing else.
    assert all(r.staged_bytes == idx_bytes for r in rec_r[1:])
    # The plan/slab ratio is pure geometry: 4*epochs index bytes per sample
    # slot vs H*W*(3+1) uint8 sample bytes. At this toy 16 px geometry that
    # is 3.9% (asserted via the closed form); at the flagship 128 px the
    # SAME form gives 0.06% — the acceptance "per-round driver-staged bytes
    # <= 1% of the streamed slab" pin, asserted on the real geometry.
    assert idx_bytes * (TINY.img_size**2 * 4) == slab_bytes * (4 * EPOCHS)
    assert 4 * EPOCHS <= 0.01 * (128 * 128 * 4)
    # The resident pool stays live on the mesh for every round; the rotating
    # part never exceeds two gather plans (current + overlapped next).
    for r in rec_r:
        assert pool.nbytes <= r.max_live_staged_bytes <= pool.nbytes + 2 * idx_bytes


def test_resident_segmented_trajectory_byte_identical(
    mesh, pool, variables, streamed_result
):
    """Acceptance pin (segments=10): the resident SegmentedRound — each
    segment gathering by its own epochs-axis slice of the plan — reproduces
    the streamed trajectory byte for byte through the driver."""
    seg = build_federated_round_segments(
        mesh, TINY, learning_rate=1e-3, local_epochs=EPOCHS, segments=10,
        data_placement="resident",
    )
    v_seg, rec_seg = run_mesh_federation(
        seg,
        variables,
        _idx_data_fn(pool),
        ROUNDS,
        mesh,
        data_placement="resident",
        sample_pool=pool,
    )
    v_s, rec_s = streamed_result
    _assert_trees_bytes_equal(v_seg, v_s)
    for rs, rr in zip(rec_s, rec_seg):
        for k in rs.metrics:
            np.testing.assert_array_equal(rr.metrics[k], rs.metrics[k], err_msg=k)
    # The per-segment host timeline is recorded, and staged bytes stay
    # index-only (the exact plan bytes — no slab chunks to stream).
    assert all(len(r.segments) == 10 for r in rec_seg)
    idx_bytes = N_CLIENTS * EPOCHS * STEPS * BATCH * 4
    assert all(r.staged_bytes == idx_bytes for r in rec_seg[1:])


def test_resident_chaos_replay_bit_identical(
    mesh, pool, variables, resident_round, resident_result
):
    """An injected device failure mid-federation re-stages pool AND plan
    from the retained host twin and replays the round — trajectory
    bit-identical to the unfaulted resident run (PR-3 retry path composed
    with the resident plane)."""
    from fedcrack_tpu.chaos import MESH_DEVICE_FAIL, FaultPlan, MeshChaos
    from fedcrack_tpu.chaos.plan import Fault

    plan = FaultPlan([Fault(MESH_DEVICE_FAIL, round=1)])
    v_chaos, records = run_mesh_federation(
        resident_round,
        variables,
        _idx_data_fn(pool),
        ROUNDS,
        mesh,
        data_placement="resident",
        sample_pool=pool,
        max_round_retries=1,
        fault_injector=MeshChaos(plan),
    )
    v_clean, _ = resident_result
    _assert_trees_bytes_equal(v_chaos, v_clean)
    assert records[1].retries == 1
    assert "InjectedDeviceFailure" in records[1].faults[0]
    assert not plan.pending
    # The replay's pool re-stage is real staging, charged to that round.
    assert records[1].staging_s > 0.0


def test_resident_hbm_guard_falls_back_to_streamed(
    mesh, pool, variables, streamed_round, resident_round, streamed_result
):
    """A pool the guard says doesn't fit runs the provided streamed round
    over slabs host-assembled from the same pool + plan: byte-identical
    trajectory, records honestly tagged "streamed". Without a fallback
    round the driver refuses instead of guessing."""
    v_fb, rec_fb = run_mesh_federation(
        resident_round,
        variables,
        _idx_data_fn(pool),
        ROUNDS,
        mesh,
        data_placement="resident",
        sample_pool=pool,
        streamed_round_fn=streamed_round,
        resident_limit_bytes=16,  # nothing fits 16 bytes
    )
    v_s, _ = streamed_result
    _assert_trees_bytes_equal(v_fb, v_s)
    assert all(r.data_placement == "streamed" for r in rec_fb)
    assert rec_fb[0].staged_bytes > pool.nbytes // 2  # real slabs shipped
    with pytest.raises(RuntimeError, match="does not fit"):
        run_mesh_federation(
            resident_round,
            variables,
            _idx_data_fn(pool),
            1,
            mesh,
            data_placement="resident",
            sample_pool=pool,
            resident_limit_bytes=16,
        )


# Slow-marked: the s2d model is a fresh pair of XLA compiles (different
# program than every tier-1 round above), and the tier-1 wall-clock budget
# is the binding constraint (ROADMAP's 870 s timeout — same reasoning as
# test_segmented's K in {1,2}). The HOST half of the claim — packed-pool
# assembly == packing the reference-assembled slab — is pinned tier-1 in
# test_sample_pool_contract below.
@pytest.mark.slow
def test_resident_s2d_prepacked_pool_byte_identical(mesh, variables):
    """The PR-1 staging twin composes with the resident plane: a pool
    stored pre-packed (layout="s2d") gathered on device equals the streamed
    round over the packed slab byte for byte — packing is per-sample, so it
    commutes with sample selection."""
    cfg = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4), stem_layout="s2d",
    )
    client_pools = [
        to_uint8_transport(*synth_crack_batch(POOL_N, 16, seed=30 + c))
        for c in range(N_CLIENTS)
    ]
    packed_pool = SamplePool.stack(client_pools, layout="s2d")
    ref_pool = SamplePool.stack(client_pools)
    rngs = [np.random.default_rng(40 + c) for c in range(N_CLIENTS)]
    idx = packed_pool.round_indices(rngs, 1, STEPS, BATCH)
    # Packed-pool assembly == packing the reference-assembled slab.
    imgs_packed, masks_packed = packed_pool.assemble_round_slab(idx)
    imgs_ref, _ = ref_pool.assemble_round_slab(idx)
    np.testing.assert_array_equal(imgs_packed, space_to_depth_images(imgs_ref))

    streamed = build_federated_round(mesh, cfg, learning_rate=1e-3, local_epochs=1)
    resident = build_federated_round(
        mesh, cfg, learning_rate=1e-3, local_epochs=1, data_placement="resident"
    )
    v_s, m_s = streamed(variables, imgs_packed, masks_packed, ACTIVE, N_SAMP)
    v_r, m_r = resident(
        variables, packed_pool.stage(mesh), idx, ACTIVE, N_SAMP
    )
    _assert_trees_bytes_equal(v_r, v_s)
    for k in m_s:
        np.testing.assert_array_equal(
            np.asarray(m_r[k]), np.asarray(m_s[k]), err_msg=k
        )


def test_resident_plan_bounds_checked(pool, variables, resident_round):
    """An out-of-range gather plan must raise at the round boundary:
    jnp.take's in-jit clip mode would otherwise train silently on a clamped
    (wrong) sample where the streamed fallback's numpy gather raises —
    breaking streamed==resident divergence symmetry."""
    rngs = [np.random.default_rng(50 + c) for c in range(N_CLIENTS)]
    idx = pool.round_indices(rngs, EPOCHS, STEPS, BATCH)
    bad = idx.copy()
    bad[0, 0, 0, 0] = pool.n_samples  # one past the end of the pool
    with pytest.raises(ValueError, match="outside"):
        resident_round(variables, (pool.images, pool.masks), bad, ACTIVE, N_SAMP)
    neg = idx.copy()
    neg[0, 0, 0, 0] = -1
    with pytest.raises(ValueError, match="outside"):
        resident_round(variables, (pool.images, pool.masks), neg, ACTIVE, N_SAMP)


# ---------- host-level contracts (no device programs) ----------


def test_sample_pool_contract():
    client_pools = [
        to_uint8_transport(*synth_crack_batch(10, 16, seed=c)) for c in range(2)
    ]
    pool = SamplePool.stack(client_pools)
    assert pool.n_clients == 2 and pool.n_samples == 10
    assert pool.nbytes == pool.images.nbytes + pool.masks.nbytes

    # s2d twin (host half of the device test below): gathering from the
    # packed pool == packing the gathered slab — packing is per-sample.
    packed = SamplePool.stack(client_pools, layout="s2d")
    assert packed.images.shape == (2, 10, 8, 8, 12)
    rng_pair = [np.random.default_rng(5), np.random.default_rng(6)]
    pidx = packed.round_indices(rng_pair, epochs=1, steps=2, batch_size=4)
    packed_slab, _ = packed.assemble_round_slab(pidx)
    ref_slab, _ = pool.assemble_round_slab(pidx)
    np.testing.assert_array_equal(packed_slab, space_to_depth_images(ref_slab))

    rngs = [np.random.default_rng(c) for c in range(2)]
    idx = pool.round_indices(rngs, epochs=3, steps=2, batch_size=4)
    assert idx.shape == (2, 3, 2, 4) and idx.dtype == np.int32
    # One permutation per round, tiled across epochs; drawn exactly like
    # shuffled_epoch_data (rng.permutation(n)[:need]).
    np.testing.assert_array_equal(idx[:, 0], idx[:, 1])
    want = np.random.default_rng(0).permutation(10)[:8].reshape(2, 4)
    np.testing.assert_array_equal(idx[0, 0], want)

    images, masks = pool.assemble_round_slab(idx)
    assert images.shape == (2, 2, 4, 16, 16, 3)
    np.testing.assert_array_equal(images[1], client_pools[1][0][idx[1, 0]])
    np.testing.assert_array_equal(masks[0], client_pools[0][1][idx[0, 0]])

    # Error contracts.
    with pytest.raises(ValueError, match="pool has"):
        pool.round_indices(rngs, epochs=1, steps=4, batch_size=4)
    with pytest.raises(ValueError, match="rngs"):
        pool.round_indices(rngs[:1], epochs=1, steps=1, batch_size=1)
    varying = idx.copy()
    varying[0, 1, 0, 0] = (varying[0, 1, 0, 0] + 1) % 10
    with pytest.raises(ValueError, match="epochs axis"):
        pool.assemble_round_slab(varying)
    with pytest.raises(ValueError, match="disagree"):
        SamplePool(pool.images, pool.masks[:, :5])
    with pytest.raises(ValueError, match="layout"):
        SamplePool(pool.images, pool.masks, layout="bogus")
    with pytest.raises(ValueError, match="pool size"):
        SamplePool.stack(
            [client_pools[0], (client_pools[1][0][:5], client_pools[1][1][:5])]
        )


def test_resident_pool_fits_guard(mesh):
    fits, info = resident_pool_fits(1024, mesh, limit_bytes=10_000)
    assert fits and info["reason"] == "fits"
    # Per-device share = pool / n_clients, against safety * limit.
    fits, info = resident_pool_fits(1024 * N_CLIENTS, mesh, limit_bytes=1024)
    assert not fits and "exceeds" in info["reason"]
    assert info["per_device_bytes"] == 1024
    # Env override wins over discovery; unknown limit passes open.
    os.environ["FEDCRACK_RESIDENT_HBM_LIMIT_BYTES"] = "64"
    try:
        fits, info = resident_pool_fits(10_000, mesh)
        assert not fits and info["limit_bytes"] == 64
    finally:
        del os.environ["FEDCRACK_RESIDENT_HBM_LIMIT_BYTES"]


def test_fedconfig_data_placement_and_c9_preset():
    cfg = FedConfig(data_placement="resident")
    assert FedConfig.from_json(cfg.to_json()).data_placement == "resident"
    assert FedConfig().data_placement == "streamed"
    with pytest.raises(ValueError, match="data_placement"):
        FedConfig(data_placement="hbm")

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "configs",
        "c9_resident_pool.json",
    )
    with open(path) as f:
        preset = FedConfig.from_dict(json.load(f))
    assert preset.data_placement == "resident"
    assert preset.segments == preset.local_epochs == 10


# ---------- growable pool: append/evict (round 13 satellite) ----------


def _pool_fixture(c=2, n=4, hw=8, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 255, size=(c, n, hw, hw, 3), dtype=np.uint8)
    masks = rng.integers(0, 2, size=(c, n, hw, hw, 1), dtype=np.uint8)
    return SamplePool(images.copy(), masks.copy()), images, masks


def test_pool_append_grows_and_dedups():
    pool, images, masks = _pool_fixture()
    assert pool.counts().tolist() == [4, 4]
    rng = np.random.default_rng(99)
    fresh_i = rng.integers(0, 255, size=(2, 8, 8, 3), dtype=np.uint8)
    fresh_m = rng.integers(0, 2, size=(2, 8, 8, 1), dtype=np.uint8)
    # One genuinely new sample + one byte-duplicate of an existing sample
    # + one duplicate WITHIN the batch: only the new one (once) lands.
    batch_i = np.stack([fresh_i[0], images[0, 1], fresh_i[0]])
    batch_m = np.stack([fresh_m[0], masks[0, 1], fresh_m[0]])
    kept = pool.append(0, batch_i, batch_m)
    assert kept == 1
    assert pool.counts().tolist() == [5, 4]
    assert pool.n_samples == 5  # capacity grew for ALL clients
    assert pool.images.shape[1] == pool.masks.shape[1] == 5
    np.testing.assert_array_equal(pool.images[0, 4], fresh_i[0])
    # Client 1's new capacity lane is padding outside its valid count.
    np.testing.assert_array_equal(pool.images[1, 4], 0)
    # Old samples untouched byte for byte (the host-twin/byte-oracle
    # contract survives growth).
    np.testing.assert_array_equal(pool.images[:, :4], images)
    np.testing.assert_array_equal(pool.masks[:, :4], masks)
    # Re-appending the same sample is now a no-op.
    assert pool.append(0, fresh_i[:1], fresh_m[:1]) == 0


def test_pool_append_validation():
    pool, _, _ = _pool_fixture()
    with pytest.raises(ValueError, match="client"):
        pool.append(5, np.zeros((1, 8, 8, 3), np.uint8), np.zeros((1, 8, 8, 1), np.uint8))
    with pytest.raises(ValueError, match="sample shape"):
        pool.append(0, np.zeros((1, 4, 4, 3), np.uint8), np.zeros((1, 8, 8, 1), np.uint8))
    with pytest.raises(ValueError, match="disagree"):
        pool.append(0, np.zeros((2, 8, 8, 3), np.uint8), np.zeros((1, 8, 8, 1), np.uint8))


def test_pool_evict_compacts_and_redeups():
    pool, images, masks = _pool_fixture()
    assert pool.evict(1, [0, 2]) == 2
    assert pool.counts().tolist() == [4, 2]
    assert pool.n_samples == 4  # capacity never shrinks
    # Survivors compacted to the front IN ORDER.
    np.testing.assert_array_equal(pool.images[1, 0], images[1, 1])
    np.testing.assert_array_equal(pool.images[1, 1], images[1, 3])
    np.testing.assert_array_equal(pool.images[1, 2], 0)
    # An evicted sample can come back (its digest was dropped).
    assert pool.append(1, images[1, 0:1], masks[1, 0:1]) == 1
    assert pool.counts().tolist() == [4, 3]
    with pytest.raises(ValueError, match="valid range"):
        pool.evict(1, [3])
    with pytest.raises(ValueError, match="valid range"):
        pool.evict(0, [-1])


def test_pool_round_indices_respects_valid_counts():
    pool, images, masks = _pool_fixture(c=2, n=6)
    pool.evict(0, [4, 5])  # client 0 down to 4 valid samples
    rngs = [np.random.default_rng(i) for i in range(2)]
    idx = pool.round_indices(rngs, epochs=1, steps=2, batch_size=2)
    assert int(idx[0].max()) < 4  # never indexes a retired lane
    assert int(idx[1].max()) < 6
    # A round that needs more than the valid count fails loudly.
    with pytest.raises(ValueError, match="valid samples"):
        pool.round_indices(
            [np.random.default_rng(0), np.random.default_rng(1)],
            epochs=1, steps=3, batch_size=2,
        )


def test_pool_untouched_rng_consumption_unchanged():
    """Byte-oracle parity retained: an untouched pool draws EXACTLY the
    pre-growable permutation (permutation over the full pool), so every
    existing resident==streamed pin keeps holding."""
    pool, _, _ = _pool_fixture(c=1, n=8)
    idx = pool.round_indices([np.random.default_rng(5)], epochs=2, steps=2, batch_size=2)
    want = np.random.default_rng(5).permutation(8)[:4].reshape(2, 2)
    np.testing.assert_array_equal(idx[0, 0], want)
    np.testing.assert_array_equal(idx[0, 1], want)  # epoch-tiled


def test_pool_append_then_assemble_slab_parity():
    """assemble_round_slab over a grown pool is still the device gather's
    byte oracle: pool[idx] on host == take(pool, idx) on device — growth
    only appends lanes, it never moves existing bytes."""
    pool, images, masks = _pool_fixture()
    rng = np.random.default_rng(123)
    pool.append(
        0,
        rng.integers(0, 255, size=(1, 8, 8, 3), dtype=np.uint8),
        rng.integers(0, 2, size=(1, 8, 8, 1), dtype=np.uint8),
    )
    idx = np.broadcast_to(
        np.array([[[4, 0], [1, 2]], [[3, 0], [1, 2]]], np.int32).reshape(2, 1, 2, 2),
        (2, 1, 2, 2),
    )
    slab_i, slab_m = pool.assemble_round_slab(idx)
    for c in range(2):
        np.testing.assert_array_equal(slab_i[c], pool.images[c][idx[c, 0]])
        np.testing.assert_array_equal(slab_m[c], pool.masks[c][idx[c, 0]])


def test_pool_s2d_append_packs_like_ctor():
    """An s2d pool packs appended samples through the same
    space_to_depth_images twin the constructor uses — gathering from the
    grown packed pool stays byte-identical to packing the gathered slab."""
    from fedcrack_tpu.data.pipeline import space_to_depth_images

    rng = np.random.default_rng(7)
    images = rng.integers(0, 255, size=(1, 2, 8, 8, 3), dtype=np.uint8)
    masks = rng.integers(0, 2, size=(1, 2, 8, 8, 1), dtype=np.uint8)
    pool = SamplePool(images, masks, layout="s2d")
    extra_i = rng.integers(0, 255, size=(1, 8, 8, 3), dtype=np.uint8)
    extra_m = rng.integers(0, 2, size=(1, 8, 8, 1), dtype=np.uint8)
    assert pool.append(0, extra_i, extra_m) == 1
    np.testing.assert_array_equal(
        pool.images[0, 2], space_to_depth_images(extra_i)[0]
    )
    # Dedup keys on the STORED (packed) canon: the same reference-layout
    # sample is recognized as a duplicate.
    assert pool.append(0, extra_i, extra_m) == 0
