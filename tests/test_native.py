"""First-party native library: build, parity, fallbacks.

The C++ kernels must agree exactly with the numpy oracle (identical
half-pixel bilinear geometry) and track cv2 INTER_LINEAR within its
fixed-point rounding; CRC32C against the RFC known-answer vector.
"""

import numpy as np
import pytest

from fedcrack_tpu import native


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(42)


def test_native_builds_and_loads():
    native._load()
    assert native.AVAILABLE, "g++ is in the image; the native build must succeed"


def test_resize_normalize_matches_numpy_oracle(rng):
    img = rng.randint(0, 256, (97, 203, 3), np.uint8)  # odd sizes
    out = native.resize_normalize(img, 64)
    ref = native._resize_numpy(img, 64, 1 / 255.0, False, 0.0)
    assert out.shape == (64, 64, 3) and out.dtype == np.float32
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_resize_binarize_matches_numpy_oracle(rng):
    m = rng.randint(0, 256, (97, 203), np.uint8)
    out = native.resize_binarize(m, 64)
    # Same thresh on both sides — resize_binarize defaults to 0.5.
    ref = native._resize_numpy(m[..., None], 64, 1.0, True, 0.5)
    assert out.shape == (64, 64, 1)
    np.testing.assert_array_equal(out, ref)
    assert set(np.unique(out)).issubset({0.0, 1.0})


def test_resize_binarize_sparse_mask_threshold():
    # A single lit pixel interpolates into (0, 0.5] around its neighborhood;
    # the 0.5 default must agree with the oracle there too (this is exactly
    # the case a thresh mismatch between test and implementation hides).
    m = np.zeros((97, 203), np.uint8)
    m[10, 10] = 1
    out = native.resize_binarize(m, 64)
    ref = native._resize_numpy(m[..., None], 64, 1.0, True, 0.5)
    np.testing.assert_array_equal(out, ref)


def test_resize_u8_rounds_to_nearest(rng):
    img = rng.randint(0, 256, (97, 203, 3), np.uint8)
    out = native.resize_u8(img, 64)
    assert out.shape == (64, 64, 3) and out.dtype == np.uint8
    ref = native._resize_numpy(img, 64, 1.0, False, 0.0)
    # reassociation in the native inner product can move a value across a
    # rounding boundary vs the numpy oracle — never more than one step
    diff = np.abs(out.astype(np.int16) - np.floor(ref + 0.5).astype(np.int16))
    assert diff.max() <= 1
    assert (diff > 0).mean() < 0.01  # and only at boundaries


def test_resize_binarize_u8_matches_float_labels(rng):
    """Mask labels must be bit-identical across the two transport dtypes —
    same interpolation, same threshold, only the output dtype differs."""
    m = (rng.uniform(size=(80, 70)) > 0.6).astype(np.uint8) * 255
    u8 = native.resize_binarize_u8(m, 64)
    f32 = native.resize_binarize(m, 64)
    assert u8.shape == (64, 64, 1) and u8.dtype == np.uint8
    np.testing.assert_array_equal(u8.astype(np.float32), f32)
    assert set(np.unique(u8)).issubset({0, 1})


def test_resize_u8_tracks_cv2(rng):
    cv2 = pytest.importorskip("cv2")
    img = rng.randint(0, 256, (448, 448, 3), np.uint8)
    out = native.resize_u8(img, 128)
    ref = cv2.resize(img, (128, 128))
    # cv2's 11-bit fixed-point weights vs float: a few LSB, never structure
    diff = np.abs(out.astype(np.int16) - ref.astype(np.int16))
    assert diff.max() <= 3


def test_resize_tracks_cv2_within_fixed_point_rounding(rng):
    cv2 = pytest.importorskip("cv2")
    img = rng.randint(0, 256, (448, 448, 3), np.uint8)
    out = native.resize_normalize(img, 128)
    ref = cv2.resize(img, (128, 128)).astype(np.float32) / 255.0
    # cv2 INTER_LINEAR uses 11-bit fixed-point weights; ~1 LSB differences
    np.testing.assert_allclose(out, ref, atol=3 / 255.0)


def test_upscale_geometry(rng):
    img = rng.randint(0, 256, (16, 16, 3), np.uint8)
    out = native.resize_normalize(img, 32)
    ref = native._resize_numpy(img, 32, 1 / 255.0, False, 0.0)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_crc32c_known_answer():
    # RFC 3720 test vector
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native._crc32c_python(b"123456789") == 0xE3069283
    assert native.crc32c(b"") == 0
    # streaming/chaining not supported; full-buffer parity native vs python
    data = bytes(range(256)) * 13
    assert native.crc32c(data) == native._crc32c_python(data)


def test_mask_binarize_matches_cv2(rng):
    """The PIL+native fallback must produce label-identical masks to the cv2
    path (uint8-domain resize-then->0), or training targets silently differ
    at mask boundaries depending on which decoder is installed."""
    cv2 = pytest.importorskip("cv2")
    for trial in range(5):
        h, w = rng.randint(40, 300, 2)
        m = (rng.uniform(size=(h, w)) > 0.7).astype(np.uint8) * 255
        via_cv2 = (cv2.resize(m, (64, 64)) > 0).astype(np.float32)
        via_native = native.resize_binarize(m, 64)[..., 0]
        np.testing.assert_array_equal(via_cv2, via_native)


def test_crc32c_ndarray_inputs(rng):
    """ndarray checksums cover the full C-order byte image regardless of
    dtype or layout, and agree with the checksum of the equivalent bytes."""
    f = rng.randn(37).astype(np.float32)
    assert native.crc32c(f) == native.crc32c(f.tobytes())
    noncontig = rng.randint(0, 256, 64, np.uint8)[::2]
    assert native.crc32c(noncontig) == native.crc32c(noncontig.tobytes())
    multi = rng.randn(5, 7).astype(np.float64)
    assert native.crc32c(multi) == native.crc32c(multi.tobytes())
    assert native.crc32c(np.empty(0, np.uint8)) == 0


def test_weighted_accumulate_and_scale(rng):
    acc = rng.randn(4097).astype(np.float32)
    x = rng.randn(4097).astype(np.float32)
    expect = acc + np.float32(0.375) * x
    native.weighted_accumulate(acc, x, 0.375)
    # FMA contraction (g++ -O3 -march=native) rounds once where numpy
    # rounds twice: 1-ulp differences are expected
    np.testing.assert_allclose(acc, expect, rtol=1e-5, atol=1e-6)
    expect = acc * np.float32(0.5)
    native.scale_inplace(acc, 0.5)
    np.testing.assert_allclose(acc, expect, rtol=1e-6, atol=1e-7)


def test_weighted_accumulate_validates():
    with pytest.raises(ValueError, match="float32"):
        native.weighted_accumulate(
            np.zeros(4, np.float64), np.zeros(4, np.float32), 1.0
        )
    with pytest.raises(ValueError, match="mismatch"):
        native.weighted_accumulate(
            np.zeros(4, np.float32), np.zeros(5, np.float32), 1.0
        )


def test_fedavg_native_path_matches_jnp(rng):
    """The gRPC server's aggregation (all-f32-numpy trees) takes the native
    accumulate/scale kernels; the result must match the jnp path bit-for-ulp.
    Device-array trees must silently take the jnp path."""
    import jax
    import jax.numpy as jnp

    from fedcrack_tpu.fed.algorithms import _fedavg_native, fedavg

    def tree(seed):
        r = np.random.RandomState(seed)
        return {
            "params": {"w": r.randn(33, 7).astype(np.float32)},
            "batch_stats": {"bn": {"mean": r.randn(129).astype(np.float32)}},
        }

    updates = [tree(s) for s in range(3)]
    weights = [8.0, 16.0, 8.0]
    assert _fedavg_native(updates, weights) is not None  # fast path engaged
    got = fedavg(updates, weights)
    jnp_updates = [jax.tree_util.tree_map(jnp.asarray, u) for u in updates]
    want = fedavg(jnp_updates, weights)
    for g, w in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)
    ):
        assert isinstance(g, np.ndarray)
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6, atol=1e-7)
    # mixed dtype falls back (returns None from the native probe)
    bad = [tree(0), tree(1)]
    bad[1]["params"]["w"] = bad[1]["params"]["w"].astype(np.float64)
    assert _fedavg_native(bad, [1.0, 1.0]) is None


def test_load_example_without_cv2(tmp_path, monkeypatch, rng):
    """The pipeline decodes via PIL + native when cv2 is unavailable."""
    from PIL import Image

    from fedcrack_tpu.data import pipeline

    img = rng.randint(0, 256, (64, 64, 3), np.uint8)
    mask = (rng.uniform(size=(64, 64)) > 0.7).astype(np.uint8) * 255
    img_p = tmp_path / "a.png"
    mask_p = tmp_path / "m.png"
    Image.fromarray(img).save(img_p)
    Image.fromarray(mask, mode="L").save(mask_p)

    monkeypatch.setattr(pipeline, "_CV2", None)
    monkeypatch.setattr(pipeline, "_CV2_PROBED", True)
    image, m = pipeline.load_example(str(img_p), str(mask_p), 32)
    assert image.shape == (32, 32, 3) and image.dtype == np.float32
    assert m.shape == (32, 32, 1) and set(np.unique(m)).issubset({0.0, 1.0})
    assert 0.0 <= image.min() and image.max() <= 1.0
