"""Checkpoint/resume: orbax round-trip + coordinator resume semantics.

Capability the reference lacks entirely (SURVEY.md §5.4: files written, never
restored; a restarted server forgets rounds).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedcrack_tpu.ckpt import (
    FedCheckpoint,
    FedCheckpointer,
    restore_server_state,
    save_server_state,
)
from fedcrack_tpu.configs import FedConfig, ModelConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.train.local import create_train_state

TINY = ModelConfig(
    img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)


def tiny_config(**kw) -> FedConfig:
    defaults = dict(
        max_rounds=3,
        cohort_size=2,
        local_epochs=1,
        registration_window_s=100.0,
        model=TINY,
        data=dataclasses.replace(FedConfig().data, img_size=16),
    )
    defaults.update(kw)
    return FedConfig(**defaults)


def tiny_variables(seed: int = 0):
    return create_train_state(jax.random.key(seed), TINY).variables


def assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)), a, b
    )


def test_save_restore_round_trip(tmp_path):
    variables = tiny_variables()
    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        ckptr.save(
            FedCheckpoint(
                current_round=2,
                model_version=1,
                variables=variables,
                history=({"round": 1, "clients": ["a", "b"]},),
            )
        )
        restored = ckptr.restore(template=variables)
    assert restored.current_round == 2
    assert restored.model_version == 1
    assert restored.history[0]["clients"] == ["a", "b"]
    assert_trees_equal(restored.variables, variables)


def test_log_buffers_ride_binary_sidecar_with_cap(tmp_path):
    """Accumulated log uploads are checkpointed as a binary item, never as
    base64 inside the JSON metadata; a multi-MB buffer keeps the metadata
    file proportionate, and buffers over the cap are dropped largest-first
    (the checkpoint stays valid — the live upload is unaffected)."""
    variables = tiny_variables()
    big = bytes(range(256)) * (4 * 4096)   # 4 MiB
    small = b"metrics\n" * 100
    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        ckptr.save(
            FedCheckpoint(
                1, 1, variables, logs={"a/big.bin": big, "a/metrics.jsonl": small}
            )
        )
        restored = ckptr.restore(template=variables)
    assert restored.logs == {"a/big.bin": big, "a/metrics.jsonl": small}
    # the JSON metadata stays small — the bytes live in the binary item
    metas = [p for p in (tmp_path / "ckpt").rglob("*") if p.is_file() and "meta" in str(p)]
    assert metas, "expected a metadata file in the checkpoint layout"
    assert all(p.stat().st_size < 64 * 1024 for p in metas), [
        (str(p), p.stat().st_size) for p in metas
    ]

    # over-cap: the big buffer is dropped, the small one survives
    with FedCheckpointer(
        tmp_path / "capped", max_log_bytes=1024 * 1024
    ) as ckptr:
        ckptr.save(
            FedCheckpoint(
                1, 1, variables, logs={"a/big.bin": big, "a/metrics.jsonl": small}
            )
        )
        restored = ckptr.restore(template=variables)
    assert restored.logs == {"a/metrics.jsonl": small}


def test_restore_empty_dir_returns_none(tmp_path):
    with FedCheckpointer(tmp_path / "empty") as ckptr:
        assert ckptr.restore() is None
        assert ckptr.latest_version() is None


def test_latest_version_wins(tmp_path):
    variables = tiny_variables()
    bumped = jax.tree_util.tree_map(lambda x: x + 1.0, variables)
    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        ckptr.save(FedCheckpoint(2, 1, variables))
        ckptr.save(FedCheckpoint(3, 2, bumped))
        restored = ckptr.restore(template=variables)
    assert restored.model_version == 2
    assert restored.current_round == 3
    assert_trees_equal(restored.variables, bumped)


def _run_one_round(state: R.ServerState, variables) -> R.ServerState:
    """Drive the pure state machine through enroll + one full round."""
    blob = tree_to_bytes(variables)
    state, _ = R.transition(state, R.Ready(cname="a", now=0.0))
    state, _ = R.transition(state, R.Ready(cname="b", now=0.1))
    state, _ = R.transition(
        state, R.TrainDone(cname="a", round=state.current_round, blob=blob,
                           num_samples=4, now=1.0)
    )
    state, reply = R.transition(
        state, R.TrainDone(cname="b", round=state.current_round, blob=blob,
                           num_samples=4, now=1.1)
    )
    assert reply.status in (R.RESP_ARY, R.FIN)
    return state


def test_server_state_checkpoint_resume(tmp_path):
    """After round 1 is checkpointed, a 'restarted' coordinator resumes at
    round 2 with the averaged weights and history intact."""
    cfg = tiny_config()
    variables = tiny_variables()
    state = R.initial_state(cfg, variables)
    state = _run_one_round(state, variables)
    assert state.current_round == 2 and state.model_version == 1

    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        save_server_state(ckptr, state)
        resumed = restore_server_state(ckptr, cfg, template=variables)

    assert resumed is not None
    assert resumed.phase == R.PHASE_ENROLL  # fresh cohort must enroll
    assert resumed.current_round == 2
    assert resumed.model_version == 1
    assert len(resumed.history) == 1
    assert_trees_equal(
        tree_from_bytes(resumed.global_blob), tree_from_bytes(state.global_blob)
    )
    # the resumed machine keeps federating: a new cohort can finish round 2
    resumed = _run_one_round(resumed, variables)
    assert resumed.current_round == 3
    assert resumed.model_version == 2


def test_resume_past_max_rounds_is_finished(tmp_path):
    cfg = tiny_config(max_rounds=1)
    variables = tiny_variables()
    state = R.initial_state(dataclasses.replace(cfg, max_rounds=3), variables)
    state = _run_one_round(state, variables)  # now current_round=2
    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        save_server_state(ckptr, state)
        resumed = restore_server_state(ckptr, cfg)  # max_rounds=1 < round 2
    assert resumed.phase == R.PHASE_FINISHED


def test_fedserver_checkpoints_and_resumes(tmp_path):
    """The transport-layer wiring: FedServer saves after each aggregation and
    a new FedServer instance over the same directory resumes."""
    import asyncio

    from fedcrack_tpu.transport.service import FedServer

    cfg = tiny_config()
    variables = tiny_variables()
    blob = tree_to_bytes(variables)

    async def run_round(server):
        await server._apply(R.Ready(cname="a", now=0.0))
        await server._apply(R.Ready(cname="b", now=0.1))
        await server._apply(R.LogChunk(cname="a", title="tb", data=b"ev1", now=0.5))
        rnd = server.state.current_round
        await server._apply(
            R.TrainDone(cname="a", round=rnd, blob=blob, num_samples=4, now=1.0)
        )
        await server._apply(
            R.TrainDone(cname="b", round=rnd, blob=blob, num_samples=4, now=1.1)
        )
        # saves run as background tasks; drain before the loop closes
        if server._bg_tasks:
            await asyncio.gather(*tuple(server._bg_tasks))

    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        first = FedServer(cfg, variables, checkpointer=ckptr)
        asyncio.run(run_round(first))
        assert first.state.model_version == 1
        assert ckptr.latest_version() == 1

        second = FedServer(cfg, variables, checkpointer=ckptr)
        assert second.state.current_round == 2
        assert second.state.model_version == 1
        assert second.state.phase == R.PHASE_ENROLL
        # client-uploaded log chunks survive the restart too
        assert second.state.logs == {"a/tb": b"ev1"}


def test_restore_without_template_gives_host_arrays(tmp_path):
    variables = tiny_variables()
    with FedCheckpointer(tmp_path / "ckpt") as ckptr:
        ckptr.save(FedCheckpoint(1, 0, variables))
        restored = ckptr.restore()
    leaves = jax.tree_util.tree_leaves(restored.variables)
    assert leaves, "restored tree is empty"
    assert_trees_equal(restored.variables, variables)


def test_fedopt_moments_survive_restart(tmp_path):
    """A restarted FedAvgM coordinator resumes its momentum instead of
    silently restarting it from zero."""
    import dataclasses

    from fedcrack_tpu.ckpt import FedCheckpointer, restore_server_state, save_server_state
    from fedcrack_tpu.configs import FedConfig
    from fedcrack_tpu.fed import rounds as R
    from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes

    cfg = FedConfig(
        cohort_size=1,
        max_rounds=4,
        registration_window_s=1.0,
        server_optimizer="fedavgm",
        server_lr=1.0,
        server_momentum=0.9,
    )
    tree = lambda v: {"params": {"w": np.full(3, float(v), np.float32)}}

    def drive(state, uploads, t0=0.0):
        state, _ = R.transition(state, R.Ready("a", now=t0))
        state, _ = R.transition(state, R.Tick(now=t0 + 2.0))
        for rnd, up in uploads:
            state, _ = R.transition(
                state,
                R.TrainDone("a", round=rnd, blob=tree_to_bytes(tree(up)),
                            num_samples=4, now=t0 + rnd),
            )
        return state

    # Uninterrupted run: rounds 1 and 2.
    s_full = drive(R.initial_state(cfg, tree(0.0)), [(1, 5.0), (2, 5.0)])
    want = tree_from_bytes(s_full.global_blob)["params"]["w"]

    # Interrupted run: round 1, checkpoint, restart, round 2.
    s1 = drive(R.initial_state(cfg, tree(0.0)), [(1, 5.0)])
    with FedCheckpointer(tmp_path) as ck:
        save_server_state(ck, s1)
        resumed = restore_server_state(ck, cfg, tree(0.0))
    assert resumed is not None and resumed.server_opt_state is not None
    s2 = drive(resumed, [(2, 5.0)], t0=100.0)
    got = tree_from_bytes(s2.global_blob)["params"]["w"]

    # FedAvgM closed form: x2 = 9.5 (momentum carries round 1's pseudo-grad);
    # without resumed moments the restart would give x2 = 5.0.
    np.testing.assert_allclose(got, want, rtol=1e-6)
    np.testing.assert_allclose(got, 9.5, rtol=1e-6)


# ---------- mid-round durable statefile (round 8) ----------


class TestStatefile:
    def _tree(self, v):
        return {"params": {"w": np.full(3, float(v), np.float32)}}

    def _cfg(self, **kw):
        defaults = dict(
            cohort_size=2, max_rounds=3, registration_window_s=100.0
        )
        defaults.update(kw)
        return FedConfig(**defaults)

    def test_roundtrip_preserves_mid_round_state(self, tmp_path):
        from fedcrack_tpu.ckpt import load_state_file, save_state_file

        cfg = self._cfg()
        state = R.initial_state(cfg, self._tree(0.0))
        state, _ = R.transition(state, R.Ready("a", now=0.0))
        state, _ = R.transition(state, R.Ready("b", now=0.1))
        blob = tree_to_bytes(self._tree(5.0))
        state, _ = R.transition(
            state, R.TrainDone("a", round=1, blob=blob, num_samples=4, now=1.0)
        )
        state, _ = R.transition(
            state, R.LogChunk("a", "tb", b"ev", now=1.5)
        )
        path = str(tmp_path / "state.msgpack")
        save_state_file(path, state)
        restored = load_state_file(path, cfg)
        assert restored.phase == R.PHASE_RUNNING
        assert restored.current_round == 1
        assert restored.cohort == frozenset({"a", "b"})
        assert restored.received == {"a": (blob, 4)}
        assert restored.logs == {"a/tb": b"ev"}
        # Clock-domain fields never survive: they re-arm on the first event.
        assert restored.round_started_at is None
        assert restored.enroll_opened_at is None
        # ... and the restored machine completes the round bit-for-bit.
        restored, rep = R.transition(
            restored,
            R.TrainDone(
                "b", round=1, blob=tree_to_bytes(self._tree(7.0)),
                num_samples=4, now=100.0,
            ),
        )
        assert rep.status == R.RESP_ARY
        got = tree_from_bytes(restored.global_blob)["params"]["w"]
        np.testing.assert_allclose(got, 6.0)

    def test_roundtrip_preserves_fedopt_moments(self, tmp_path):
        """A FedAvgM coordinator's momentum survives the statefile exactly
        like the orbax path (closed form: x2 = 9.5, not the moment-less
        5.0)."""
        from fedcrack_tpu.ckpt import load_state_file, save_state_file

        cfg = self._cfg(
            cohort_size=1,
            registration_window_s=1.0,
            server_optimizer="fedavgm",
            server_lr=1.0,
            server_momentum=0.9,
        )
        state = R.initial_state(cfg, self._tree(0.0))
        state, _ = R.transition(state, R.Ready("a", now=0.0))
        state, _ = R.transition(state, R.Tick(now=2.0))
        state, _ = R.transition(
            state,
            R.TrainDone("a", round=1, blob=tree_to_bytes(self._tree(5.0)),
                        num_samples=4, now=3.0),
        )
        assert state.server_opt_state is not None
        path = str(tmp_path / "state.msgpack")
        save_state_file(path, state)
        restored = load_state_file(path, cfg)
        assert restored.server_opt_state is not None
        restored, _ = R.transition(
            restored,
            R.TrainDone("a", round=2, blob=tree_to_bytes(self._tree(5.0)),
                        num_samples=4, now=100.0),
        )
        got = tree_from_bytes(restored.global_blob)["params"]["w"]
        np.testing.assert_allclose(got, 9.5, rtol=1e-6)

    def test_corrupt_statefile_returns_none(self, tmp_path):
        from fedcrack_tpu.ckpt import load_state_file

        path = tmp_path / "state.msgpack"
        path.write_bytes(b"\x00 not msgpack at all")
        assert load_state_file(str(path), self._cfg()) is None
        assert load_state_file(str(tmp_path / "missing"), self._cfg()) is None

    def test_fedserver_statefile_beats_checkpoint_at_same_version(self, tmp_path):
        """Both persistence layers populated at model_version 1, the
        statefile additionally holding round-2's first received update: the
        boot must pick the statefile (same version -> strictly more state),
        but a STALE statefile loses to a newer checkpoint."""
        import asyncio

        from fedcrack_tpu.ckpt import save_state_file
        from fedcrack_tpu.transport.service import FedServer

        cfg = self._cfg(state_path=str(tmp_path / "state.msgpack"))
        variables = self._tree(0.0)
        blob = tree_to_bytes(variables)

        async def run_one_round(server):
            await server._apply(R.Ready(cname="a", now=0.0))
            await server._apply(R.Ready(cname="b", now=0.1))
            rnd = server.state.current_round
            await server._apply(
                R.TrainDone(cname="a", round=rnd, blob=blob, num_samples=4, now=1.0)
            )
            await server._apply(
                R.TrainDone(cname="b", round=rnd, blob=blob, num_samples=4, now=1.1)
            )
            # round 2 partially collected: a reports, then the "kill"
            await server._apply(
                R.TrainDone(cname="a", round=rnd + 1, blob=blob, num_samples=4, now=2.0)
            )
            if server._bg_tasks:
                await asyncio.gather(*tuple(server._bg_tasks))

        with FedCheckpointer(tmp_path / "ckpt") as ckptr:
            first = FedServer(cfg, variables, checkpointer=ckptr)
            asyncio.run(run_one_round(first))
            assert ckptr.latest_version() == 1

            second = FedServer(cfg, variables, checkpointer=ckptr)
            # Statefile won: same model_version, but mid-round state intact.
            assert second.state.phase == R.PHASE_RUNNING
            assert second.state.current_round == 2
            assert set(second.state.received) == {"a"}
            assert second.state.cohort == frozenset({"a", "b"})

            # A stale statefile (pre-aggregation snapshot) must LOSE to the
            # newer checkpoint.
            stale = R.initial_state(cfg, variables)
            save_state_file(cfg.state_path, stale)  # model_version 0
            third = FedServer(cfg, variables, checkpointer=ckptr)
            assert third.state.model_version == 1
            assert third.state.phase == R.PHASE_ENROLL  # the orbax semantics
