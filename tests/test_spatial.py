"""Spatial context parallelism: halo-exchange sharded U-Net vs one device.

The sharded forward/train step must be numerically identical to the
single-device model on the SAME variables pytree (parallel/spatial.py);
these are the golden cross-checks (SURVEY.md §4 pattern: mesh == host)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models.resunet import init_variables, predict
from fedcrack_tpu.jaxcompat import shard_map
from fedcrack_tpu.parallel.spatial import (
    build_spatial_predict,
    build_spatial_train_step,
    halo_exchange,
    make_spatial_mesh,
)
from fedcrack_tpu.train.local import create_train_state, train_step

CFG = ModelConfig(img_size=64)


def _variables_and_batch(batch=2, h=64, w=64, seed=0):
    rng = jax.random.key(seed)
    variables = init_variables(rng, CFG)
    kimg, kmask = jax.random.split(jax.random.key(seed + 1))
    images = jax.random.uniform(kimg, (batch, h, w, 3), jnp.float32)
    masks = (jax.random.uniform(kmask, (batch, h, w, 1)) > 0.7).astype(jnp.float32)
    return variables, np.asarray(images), np.asarray(masks)


def test_halo_exchange_neighbor_rows_and_edge_fill():
    mesh = make_spatial_mesh(4)
    x = np.arange(8 * 2, dtype=np.float32).reshape(1, 8, 2, 1)

    def body(xs):
        return halo_exchange(xs, "space", 4, up=1, down=1, fill=0.0)

    out = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=P(None, "space"), out_specs=P(None, "space")
        )
    )(x)
    out = np.asarray(out).reshape(4, 4, 2)  # 4 shards x (1 up + 2 own + 1 down)
    ref = x.reshape(8, 2)
    for s in range(4):
        own = ref[2 * s : 2 * s + 2]
        up = ref[2 * s - 1] if s > 0 else np.zeros(2, np.float32)
        down = ref[2 * s + 2] if s < 3 else np.zeros(2, np.float32)
        np.testing.assert_array_equal(out[s], np.stack([up, *own, down]))


def test_spatial_predict_matches_single_device():
    variables, images, _ = _variables_and_batch()
    want = np.asarray(predict(variables, images, CFG))

    mesh = make_spatial_mesh(4)
    predict_fn = build_spatial_predict(mesh, CFG)
    got = np.asarray(predict_fn(variables, images))

    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_spatial_predict_with_data_axis():
    variables, images, _ = _variables_and_batch(batch=2)
    want = np.asarray(predict(variables, images, CFG))

    mesh = make_spatial_mesh(4, n_data=2)
    predict_fn = build_spatial_predict(mesh, CFG)
    got = np.asarray(predict_fn(variables, images))

    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_spatial_predict_bfloat16_config():
    """bf16 compute configs must track the single-device bf16 model (loose
    tolerance — bf16 rounding), not silently promote to float32."""
    cfg = ModelConfig(img_size=64, compute_dtype="bfloat16")
    variables, images, _ = _variables_and_batch()
    want = np.asarray(predict(variables, images, cfg), np.float32)

    mesh = make_spatial_mesh(4)
    got = np.asarray(build_spatial_predict(mesh, cfg)(variables, images), np.float32)

    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.05)


def test_spatial_predict_rejects_misaligned_height():
    mesh = make_spatial_mesh(4)
    predict_fn = build_spatial_predict(mesh, CFG)
    variables, _, _ = _variables_and_batch()
    bad = np.zeros((1, 48, 64, 3), np.float32)  # 48 % (16*4) != 0
    with pytest.raises(ValueError, match="multiple of 16"):
        predict_fn(variables, bad)


def test_spatial_train_step_matches_single_device():
    """Gradient + sync-BN parity. The sharded step runs with SGD(1.0) so the
    param delta IS the (pmean-ed) gradient — Adam's g/|g| normalization
    would amplify fp-associativity noise on near-zero gradients into
    arbitrary relative error, which tests nothing."""
    variables, images, masks = _variables_and_batch()

    # Single-device reference: gradient of the identical loss.
    from fedcrack_tpu.models import ResUNet
    from fedcrack_tpu.ops.pallas_bce import fused_segmentation_metrics

    model = ResUNet(config=CFG)

    def loss_fn(params):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        m = fused_segmentation_metrics(logits, jnp.asarray(masks))
        return m["loss"], (m["loss"], mutated["batch_stats"])

    (_, (ref_loss, ref_stats)), ref_grads = jax.jit(
        jax.value_and_grad(loss_fn, has_aux=True)
    )(variables["params"])

    # Sharded step over 4 spatial shards on the same variables.
    import optax

    mesh = make_spatial_mesh(4)
    step_fn = build_spatial_train_step(mesh, CFG, tx=optax.sgd(1.0))
    opt_state = step_fn.tx.init(variables["params"])
    new_params, new_stats, _, metrics = step_fn(
        variables["params"], variables["batch_stats"], opt_state, images, masks
    )
    sharded_grads = jax.tree_util.tree_map(
        lambda old, new: old - new, variables["params"], new_params
    )

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_loss), rtol=1e-5, atol=1e-6
    )
    # Both sides are float32 renditions of the same math (verified exact to
    # 5e-9 against a float64 oracle), each ~1e-5 relative-L2 from the true
    # gradient — so compare norms per leaf, not elements: elementwise ratios
    # are meaningless where the true gradient is ~0 (e.g. conv biases feeding
    # BatchNorm, whose gradient cancels exactly).
    def assert_close_norm(a, b):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        err = np.linalg.norm(a - b)
        assert err <= 5e-3 * np.linalg.norm(b) + 1e-5, (
            f"gradient leaf off by ||d||={err:.3e} vs ||ref||={np.linalg.norm(b):.3e}"
        )

    jax.tree_util.tree_map(assert_close_norm, sharded_grads, ref_grads)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        new_stats,
        ref_stats,
    )
