"""Keras h5 -> Flax importer: tensor-for-tensor forward-pass parity.

Builds the reference's exact U-Net architecture in Keras (from the
SURVEY.md §2.3 spec: stem Conv/2 + BN + ReLU; encoder blocks of two
ReLU->SeparableConv->BN then MaxPool(3,/2) with strided 1x1 residual;
decoder blocks of two ReLU->ConvT->BN then x2 upsample with upsampled 1x1
residual; 1x1 sigmoid head), saves a legacy full-model h5 (the
``ModelCheckpoint`` format of test/Segmentation.py:177-179), imports it, and
checks the Flax model reproduces Keras' forward pass to float tolerance.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax
import jax.numpy as jnp

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models import ResUNet
from fedcrack_tpu.tools.h5_import import import_resunet_h5, read_keras_h5

TINY = ModelConfig(
    img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
)


def build_keras_resunet(config: ModelConfig) -> "tf.keras.Model":
    """The reference architecture (SURVEY.md §2.3), in Keras."""
    layers = tf.keras.layers
    inputs = tf.keras.Input(shape=config.input_shape)
    x = layers.Conv2D(config.stem_features, 3, strides=2, padding="same")(inputs)
    x = layers.BatchNormalization()(x)
    x = layers.Activation("relu")(x)
    previous = x
    for f in config.encoder_features:
        x = layers.Activation("relu")(x)
        x = layers.SeparableConv2D(f, 3, padding="same")(x)
        x = layers.BatchNormalization()(x)
        x = layers.Activation("relu")(x)
        x = layers.SeparableConv2D(f, 3, padding="same")(x)
        x = layers.BatchNormalization()(x)
        x = layers.MaxPooling2D(3, strides=2, padding="same")(x)
        residual = layers.Conv2D(f, 1, strides=2, padding="same")(previous)
        x = layers.add([x, residual])
        previous = x
    for f in config.decoder_features:
        x = layers.Activation("relu")(x)
        x = layers.Conv2DTranspose(f, 3, padding="same")(x)
        x = layers.BatchNormalization()(x)
        x = layers.Activation("relu")(x)
        x = layers.Conv2DTranspose(f, 3, padding="same")(x)
        x = layers.BatchNormalization()(x)
        x = layers.UpSampling2D(2)(x)
        residual = layers.UpSampling2D(2)(previous)
        residual = layers.Conv2D(f, 1, padding="same")(residual)
        x = layers.add([x, residual])
        previous = x
    outputs = layers.Conv2D(config.num_classes, 1, padding="same",
                            activation="sigmoid")(x)
    return tf.keras.Model(inputs, outputs)


def randomize_weights(model: "tf.keras.Model", seed: int = 0) -> None:
    """Random weights INCLUDING BatchNorm moving stats, so the import parity
    check exercises the batch_stats path too."""
    rng = np.random.RandomState(seed)
    new = []
    for w in model.get_weights():
        if w.ndim == 1 and np.all(w >= 0) and np.all(w <= 1) and np.any(w > 0):
            # moving_variance / gamma start at 1: keep positive
            new.append(rng.uniform(0.5, 1.5, w.shape).astype(np.float32))
        else:
            new.append(rng.normal(0, 0.5, w.shape).astype(np.float32))
    model.set_weights(new)


@pytest.fixture(scope="module")
def keras_h5(tmp_path_factory):
    model = build_keras_resunet(TINY)
    randomize_weights(model)
    path = tmp_path_factory.mktemp("h5") / "crack_segmentation.h5"
    model.save(path)  # legacy full-model h5: the reference's checkpoint format
    return model, str(path)


def test_read_keras_h5_layer_inventory(keras_h5):
    _, path = keras_h5
    layers = read_keras_h5(path)
    kinds = [l.kind for l in layers]
    # tiny config: 1 enc block, 2 dec blocks
    assert kinds.count("separable") == 2
    assert kinds.count("convT") == 4
    assert kinds.count("bn") == 1 + 2 + 4
    assert kinds.count("conv") == 1 + 1 + 2 + 1  # stem, enc res, dec res, head


def test_forward_pass_parity(keras_h5):
    model, path = keras_h5
    variables = import_resunet_h5(path, TINY)

    rng = np.random.RandomState(7)
    images = rng.uniform(0, 1, (2, *TINY.input_shape)).astype(np.float32)

    y_keras = model.predict(images, verbose=0)
    logits = ResUNet(config=TINY).apply(variables, jnp.asarray(images), train=False)
    y_flax = np.asarray(jax.nn.sigmoid(logits))

    assert y_flax.shape == y_keras.shape
    np.testing.assert_allclose(y_flax, y_keras, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("img", [128, 256])
def test_s2d_layout_bit_exact_from_imported_keras_weights(keras_h5, img):
    """The round-6 transform pin, fed from REAL imported Keras weights: a
    space-to-depth model built from an h5 checkpoint produces bit-exact
    logits vs the reference layout at 128 and 256 px, on random and
    synthetic-fixture inputs. (Weights are resolution-independent: the TINY
    architecture imported at 32 px applies unchanged at larger crops; the
    layout flags never touch the importer because parameter shapes are
    layout-invariant.)"""
    import dataclasses

    from fedcrack_tpu.data.synthetic import synth_crack_batch

    _, path = keras_h5
    variables = import_resunet_h5(path, TINY)
    ref_cfg = dataclasses.replace(TINY, img_size=img)
    s2d_cfg = dataclasses.replace(
        TINY, img_size=img, stem_layout="s2d", res_layout="packed"
    )

    rng = np.random.RandomState(11)
    rand = rng.uniform(0, 1, (2, img, img, 3)).astype(np.float32)
    fixture, _ = synth_crack_batch(2, img_size=img, seed=5)
    for x in (rand, fixture):
        ref = ResUNet(config=ref_cfg).apply(variables, jnp.asarray(x), train=False)
        out = ResUNet(config=s2d_cfg).apply(variables, jnp.asarray(x), train=False)
        assert jnp.array_equal(ref, out), (
            "s2d layout diverged from reference on imported Keras weights"
        )


def test_import_shape_mismatch_raises(keras_h5):
    _, path = keras_h5
    wrong = ModelConfig(
        img_size=32, stem_features=8, encoder_features=(8,), decoder_features=(8, 4)
    )
    with pytest.raises(ValueError, match="mismatch"):
        import_resunet_h5(path, wrong)


def test_import_layer_count_mismatch_raises(keras_h5):
    _, path = keras_h5
    wrong = ModelConfig(
        img_size=32, stem_features=4, encoder_features=(8, 8), decoder_features=(8, 8, 4)
    )
    with pytest.raises(ValueError, match="count mismatch"):
        import_resunet_h5(path, wrong)


def test_imported_variables_are_trainable(keras_h5):
    """Imported weights slot straight into the training stack."""
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.train.local import create_train_state, train_step

    _, path = keras_h5
    variables = import_resunet_h5(path, TINY)
    state = create_train_state(jax.random.key(0), TINY)
    state = state.replace_variables(variables)
    state = state.replace(opt_state=state.tx.init(state.params))
    images, masks = synth_crack_batch(4, img_size=TINY.img_size, seed=0)
    state, metrics = train_step(
        state, (jnp.asarray(images), jnp.asarray(masks)), state.params,
        jnp.float32(0.0),
    )
    assert np.isfinite(float(metrics["loss"]))
