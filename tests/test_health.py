"""Federation health plane (round 18): ledger, anomaly, canary, drift.

Pins the four health subsystems' contracts:

- the per-client ledger is DETERMINISTIC — permuted arrival orders produce
  byte-identical statefile snapshots, metric expositions, and JSONL
  exports — and it survives a mid-round kill bit-for-bit;
- anomaly scoring is the robust z (median/MAD) with the 3.5
  Iglewicz-Hoaglin alert, and it flags a scaled-but-sanitation-passing
  update while leaving honest cohort members unflagged;
- the canary evaluator can never fail or block an install (it runs at the
  TAIL of the swap, wrapped), and its reference/IoU bookkeeping is exact;
- drift PSI matches the closed form, and the health SLO rules
  (configs/slo_health.json) turn a canary IoU cliff + anomaly spike into
  a watchdog breach with a flight dump and the exit-3 verdict — proved
  end to end by the SCALED_UPDATE chaos drill.
"""

import json
import math
import os
import tempfile
import types

import numpy as np
import pytest

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
from fedcrack_tpu.health import ledger as hl
from fedcrack_tpu.health.drift import DriftMonitor, psi
from fedcrack_tpu.obs import flight
from fedcrack_tpu.obs.registry import MetricsRegistry
from fedcrack_tpu.obs.watchdog import BREACH_EXIT, Watchdog, load_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEALTH_RULES = os.path.join(REPO, "configs", "slo_health.json")


def _tree(v: float):
    return {"params": {"w": np.full((4, 4), float(v), np.float32)}}


def _cfg(**kw):
    defaults = dict(
        cohort_size=3, max_rounds=2, registration_window_s=100.0
    )
    defaults.update(kw)
    return FedConfig(**defaults)


def _run_round(ready_order, done_order, values):
    """One full FedAvg round driven through the pure state machine with the
    given arrival permutations; returns the post-aggregation state."""
    state = R.initial_state(_cfg(), _tree(0.0))
    for i, name in enumerate(ready_order):
        state, rep = R.transition(state, R.Ready(name, now=0.1 * i))
        assert rep.status == R.SW
    for i, name in enumerate(done_order):
        state, rep = R.transition(
            state,
            R.TrainDone(
                name,
                round=1,
                blob=tree_to_bytes(_tree(values[name])),
                num_samples=4,
                now=1.0 + 0.1 * i,
            ),
        )
    assert state.current_round == 2  # the round closed and aggregated
    return state


# ---------- ledger determinism ----------


def test_ledger_permuted_arrivals_byte_identical(tmp_path):
    """Arrival order must never leak into the ledger's three canonical
    serializations: the r8 statefile snapshot, the anomaly exposition, and
    the JSONL export are byte-identical across permutations."""
    from fedcrack_tpu.ckpt import save_state_file

    values = {"a": 1.0, "b": 1.2, "c": 0.9}
    s1 = _run_round(["a", "b", "c"], ["a", "b", "c"], values)
    s2 = _run_round(["c", "a", "b"], ["b", "c", "a"], values)

    assert hl.ledger_to_wire(s1.ledger) == hl.ledger_to_wire(s2.ledger)

    blobs = []
    for i, state in enumerate((s1, s2)):
        path = str(tmp_path / f"state_{i}.msgpack")
        save_state_file(path, state)
        with open(path, "rb") as f:
            blobs.append(f.read())
    assert blobs[0] == blobs[1]

    expositions = []
    for state in (s1, s2):
        reg = MetricsRegistry()
        hl.export_anomaly_metrics(state.ledger, registry=reg)
        expositions.append(reg.exposition())
    assert expositions[0] == expositions[1]
    assert "fed_client_anomaly_score_ratio" in expositions[0]
    assert "fed_client_anomaly_max_ratio" in expositions[0]

    jsonls = []
    for i, state in enumerate((s1, s2)):
        path = str(tmp_path / f"ledger_{i}.jsonl")
        hl.write_ledger_jsonl(state.ledger, path)
        with open(path, "rb") as f:
            jsonls.append(f.read())
    assert jsonls[0] == jsonls[1]
    assert hl.read_ledger_jsonl(str(tmp_path / "ledger_0.jsonl")) == {
        n: s1.ledger[n] for n in s1.ledger
    }


def test_ledger_conservation_after_round():
    state = _run_round(
        ["a", "b", "c"], ["c", "b", "a"], {"a": 1.0, "b": 1.2, "c": 0.9}
    )
    cons = hl.conservation(state.ledger)
    assert cons["clients"] == 3
    assert cons["violations"] == []
    for rec in state.ledger.values():
        assert rec["offers"] == rec["accepted"] == 1


# ---------- statefile round-trip across a mid-round kill ----------


def test_ledger_survives_midround_kill(tmp_path):
    """Kill mid-round with one accepted and one sanitation-rejected offer
    on the books: the restored ledger is exactly the pre-kill ledger, a
    re-snapshot is bit-identical, and the completed round conserves."""
    from fedcrack_tpu.ckpt import load_state_file, save_state_file

    cfg = _cfg(cohort_size=2)
    state = R.initial_state(cfg, _tree(0.0))
    state, _ = R.transition(state, R.Ready("a", now=0.0))
    state, _ = R.transition(state, R.Ready("b", now=0.1))
    state, rep = R.transition(
        state,
        R.TrainDone(
            "a", round=1, blob=tree_to_bytes(_tree(2.0)), num_samples=4,
            now=1.0,
        ),
    )
    assert rep.status == R.RESP_ACY
    nan_tree = _tree(1.0)
    nan_tree["params"]["w"][0, 0] = np.nan
    state, rep = R.transition(
        state,
        R.TrainDone(
            "b", round=1, blob=tree_to_bytes(nan_tree), num_samples=4,
            now=1.5,
        ),
    )
    assert rep.status == R.REJECTED
    assert state.ledger["b"]["rejected"]["sanitation"] == 1

    path = str(tmp_path / "state.msgpack")
    save_state_file(path, state)
    restored = load_state_file(path, cfg)
    assert hl.ledger_to_wire(restored.ledger) == hl.ledger_to_wire(
        state.ledger
    )
    resnap = str(tmp_path / "state2.msgpack")
    save_state_file(resnap, restored)
    with open(path, "rb") as f1, open(resnap, "rb") as f2:
        assert f1.read() == f2.read()

    restored, rep = R.transition(
        restored,
        R.TrainDone(
            "b", round=1, blob=tree_to_bytes(_tree(4.0)), num_samples=4,
            now=100.0,
        ),
    )
    assert rep.status == R.RESP_ARY
    cons = hl.conservation(restored.ledger)
    assert cons["violations"] == []
    assert restored.ledger["b"]["offers"] == 2
    assert restored.ledger["b"]["accepted"] == 1


# ---------- anomaly scoring ----------


def test_robust_z_closed_form():
    # med=4.8, MAD=0.8: z(v) = |v - 4.8| / (1.4826*0.8 + 1e-3*4.8)
    values = [4.0, 4.8, 1200.0]
    denom = 1.4826 * 0.8 + 1e-3 * 4.8
    z = hl.robust_z(values)
    assert z[0] == pytest.approx(0.8 / denom, abs=1e-4)
    assert z[1] == 0.0
    assert z[2] == pytest.approx(1195.2 / denom, rel=1e-4)
    # Degenerate windows never divide by zero and never score.
    assert hl.robust_z([]) == []
    assert hl.robust_z([3.0]) == [0.0]
    # MAD=0 collapses to the epsilon floor, capped at SCORE_CAP.
    assert all(s <= hl.SCORE_CAP for s in hl.robust_z([1.0, 1.0, 1e9]))


def test_observe_flush_flags_scaled_update_only():
    base = _tree(0.0)
    items = [
        ("a", _tree(1.0)),
        ("b", _tree(1.2)),
        ("c", _tree(300.0)),
    ]
    ledger = {}
    for name, tree in items:
        ledger = hl.record_offer(
            ledger, name, outcome="accepted", num_samples=4,
            wire_len=128, round=1, norm=hl.update_norm(tree, base),
        )
    ledger, scores = hl.observe_flush(ledger, items, base)
    assert scores["c"] >= hl.ANOMALY_ALERT
    assert max(scores["a"], scores["b"]) < hl.ANOMALY_ALERT
    assert ledger["c"]["flags"] == 1
    assert ledger["a"]["flags"] == ledger["b"]["flags"] == 0


def test_client_label_cardinality_bounded():
    names = [f"client_{i:03d}" for i in range(100)]
    labels = {hl.client_label(n, i) for i, n in enumerate(sorted(names))}
    assert "_overflow" in labels
    # Bounded: at most MAX_CLIENT_LABELS real names + the overflow bucket.
    assert len(labels) <= hl.MAX_CLIENT_LABELS + 1


# ---------- canary ----------


class _FakeEngine:
    """Minimal engine contract for CanaryEvaluator: fixed buckets, probs
    that are a pure function of the 'installed' variables."""

    bucket_sizes = (8,)
    max_batch = 2
    serve_config = types.SimpleNamespace(
        quant_probe_batch=2, quant_probe_seed=0
    )

    def predict_bucket(self, device_variables, images_u8):
        level = float(device_variables)
        return np.full(
            (images_u8.shape[0],) + images_u8.shape[1:3], level, np.float32
        )


def test_canary_reference_then_regression():
    from fedcrack_tpu.health.canary import CanaryEvaluator

    reg = MetricsRegistry()
    canary = CanaryEvaluator(_FakeEngine(), registry=reg)
    ref = canary.evaluate(0, 0.8)
    assert ref["iou"] == 1.0 and ref["reference_version"] == 0
    same = canary.evaluate(1, 0.9)  # same masks (both sides > 0.5)
    assert same["iou"] == 1.0
    cliff = canary.evaluate(2, 0.2)  # empty mask vs full mask
    assert cliff["iou"] == 0.0
    assert [h["version"] for h in canary.history] == [0, 1, 2]
    fam = reg.get("model_canary_iou_ratio")
    assert fam is not None
    audit = canary.audit()
    assert audit["evals"] == 3 and audit["all_finite_unit"]
    assert audit["min_iou"] == 0.0


def test_canary_failure_never_blocks_swap():
    """The swap contract: a raising canary is logged and swallowed — the
    install still flips the pointer and returns True."""
    import jax

    from fedcrack_tpu.models import ModelConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve.engine import InferenceEngine, ServeConfig
    from fedcrack_tpu.serve.hot_swap import ModelVersionManager

    model_cfg = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4),
    )
    engine = InferenceEngine(
        model_cfg,
        ServeConfig(
            bucket_sizes=(16,), max_batch=2, max_delay_ms=30.0,
            tile_overlap=4,
        ),
    )
    v0 = init_variables(jax.random.key(0), model_cfg)

    class _Boom:
        calls = 0

        def evaluate(self, version, device_variables):
            _Boom.calls += 1
            raise RuntimeError("canary exploded")

    manager = ModelVersionManager(
        engine, v0, initial_version=0, canary=_Boom()
    )
    assert manager.install(1, v0) is True
    assert manager.version == 1
    assert _Boom.calls == 1
    # Stale versions are refused BEFORE the canary can run.
    assert manager.install(1, v0) is False
    assert _Boom.calls == 1


# ---------- drift PSI ----------


def test_psi_closed_form_and_units():
    ref = np.array([0.5, 0.5])
    assert psi(ref, ref) == pytest.approx(0.0, abs=1e-9)
    cur = np.array([0.9, 0.1])
    expected = (0.9 - 0.5) * math.log(0.9 / 0.5) + (0.1 - 0.5) * math.log(
        0.1 / 0.5
    )
    assert psi(ref, cur) == pytest.approx(expected, rel=1e-2)
    assert psi(ref, cur) == psi(cur, ref)  # symmetric in the closed form
    with pytest.raises(ValueError):
        psi(np.ones(3), np.ones(4))
    # Zero-mass bins are epsilon-smoothed, never inf/nan.
    assert math.isfinite(psi(np.array([1.0, 0.0]), np.array([0.0, 1.0])))


def test_drift_monitor_self_comparison_is_zero():
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(4, 8, 8, 3), dtype=np.uint8)
    probs = rng.random((4, 8, 8)).astype(np.float32)
    ref = DriftMonitor()
    ref.observe(images, probs)
    mon = DriftMonitor(reference=ref.profile())
    mon.observe(images, probs)
    psis = mon.compare()
    assert psis  # at least input/confidence/entropy signals on one bucket
    assert all(v == pytest.approx(0.0, abs=1e-9) for v in psis.values())
    for key in psis:
        bucket, signal = key.split("/", 1)
        assert bucket == "8" and signal in (
            "input", "confidence", "entropy", "crack_fraction"
        )


# ---------- watchdog: health rules breach -> flight dump -> exit 3 ----------


def _armed_ring():
    ring = flight.current()
    if ring is not None:
        return ring, lambda: None
    tmp = tempfile.mkdtemp(prefix="health_flight_")
    flight.install(path=os.path.join(tmp, "flight.jsonl"), hooks=False)
    return flight.current(), flight.uninstall


def test_health_rules_breach_dumps_flight_and_exits_3():
    reg = MetricsRegistry()
    reg.gauge("model_canary_iou_ratio", "t").set(0.2)
    reg.gauge("fed_client_anomaly_max_ratio", "t").set(9.0)
    ring, cleanup = _armed_ring()
    try:
        before = len(ring.dumps)
        watchdog = Watchdog(load_rules(HEALTH_RULES), registry=reg)
        report = watchdog.enforce()
        assert sorted(b["rule"] for b in report["breaches"]) == [
            "canary_iou_floor", "client_anomaly_ceiling"
        ]
        assert len(ring.dumps) == before + 1
        assert "canary_iou_floor" in ring.dumps[-1]["reason"]
        assert BREACH_EXIT == 3  # the soak/CI exit contract
    finally:
        cleanup()


def test_health_rules_clean_and_skip_when_absent():
    rules = load_rules(HEALTH_RULES)
    reg = MetricsRegistry()
    reg.gauge("model_canary_iou_ratio", "t").set(0.97)
    reg.gauge("fed_client_anomaly_max_ratio", "t").set(1.2)
    report = Watchdog(rules, registry=reg).evaluate()
    assert report["breaches"] == []
    # on_missing=skip: a registry without the health plane stays
    # indeterminate instead of minting a false breach.
    empty = Watchdog(rules, registry=MetricsRegistry()).evaluate()
    assert empty["breaches"] == []


# ---------- the SCALED_UPDATE drill: the full chain, end to end ----------


def test_scaled_update_drill_end_to_end():
    """The round-18 acceptance chain in one artifact: FedAvg's sanitation
    gate ACCEPTS the scaled update (finite, well-formed), the ledger's
    robust z flags exactly the scaled client, the canary IoU cliffs on the
    poisoned install without blocking the swap or recompiling, and the
    health watchdog converts the pair of signals into a breach + flight
    dump + exit-3 verdict."""
    from fedcrack_tpu.tools.chaos_drill import run_scaled_update_drill

    out = run_scaled_update_drill()
    led = out["ledger"]
    assert led["fault_fired"] == "scaled_update"
    assert led["poisoned_accepted"] and led["honest_accepted"]
    assert led["nothing_rejected"]  # sanitation saw nothing wrong
    assert led["global_drag_matches_fedavg"]  # the poison really averaged in
    assert led["poisoned_flagged"] and led["honest_below_alert"]
    assert led["flagged_flushes"] >= 1

    can = out["canary"]
    assert can["reference_iou"] == 1.0
    assert can["iou_cliff"] and can["poisoned_iou"] < 0.5
    assert can["swap_still_installed"]
    assert can["recompiles_since_warmup"] == 0  # probes reuse bucket programs

    wd = out["watchdog"]
    assert wd["both_signals_breached"]
    assert wd["flight_dumped"]
    assert wd["would_exit"] == BREACH_EXIT == 3

    # The drill's artifact is exactly what bench.py commits: schema-check it
    # with the same validator the committed artifact tests use.
    import bench

    assert bench.validate_detail({"federation_health": out}) == []


# ---------- health_report: the joined artifact ----------


def test_health_report_round_trip(tmp_path):
    from fedcrack_tpu.tools import health_report

    state = _run_round(
        ["a", "b", "c"], ["a", "b", "c"], {"a": 1.0, "b": 1.2, "c": 0.9}
    )
    ledger_path = str(tmp_path / "ledger.jsonl")
    hl.write_ledger_jsonl(state.ledger, ledger_path)
    canary_path = str(tmp_path / "canary.json")
    with open(canary_path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "history": [
                    {
                        "version": 0, "iou": 1.0, "per_bucket": {"16": 1.0},
                        "reference_version": 0, "probe_batch": 2,
                        "probe_seed": 0,
                    }
                ],
                "audit": {
                    "evals": 1, "reference_version": 0, "min_iou": 1.0,
                    "all_finite_unit": True,
                },
            },
            f,
        )
    out_path = str(tmp_path / "report.json")
    rc = health_report.main(
        ["--ledger", ledger_path, "--canary", canary_path, "--out", out_path]
    )
    assert rc == 0
    with open(out_path, encoding="utf-8") as f:
        report = json.load(f)
    assert health_report.validate_report(report) == []
    assert report["summary"]["clients"] == 3
    assert report["summary"]["conservation_violations"] == []
    # The guard trips loudly on a conservation break.
    broken = json.loads(json.dumps(report))
    next(iter(broken["clients"].values()))["offers"] = 99
    assert any(
        "conservation" in v for v in health_report.validate_report(broken)
    )


def test_health_report_joins_quarantine(tmp_path):
    """Round 21: a ledger carrying quarantine counters round-trips
    through the joined report — per-client counts typed, the summary's
    quarantines total + quarantined_clients join, schema clean — and a
    wrong-typed counter trips the guard."""
    from fedcrack_tpu.tools import health_report

    ledger = {"a": hl.new_record(), "b": hl.new_record()}
    ledger = hl.record_quarantine(ledger, "b")
    ledger = hl.record_quarantine(ledger, "b")
    ledger_path = str(tmp_path / "ledger.jsonl")
    hl.write_ledger_jsonl(ledger, ledger_path)
    report = health_report.build_report(ledger_path)
    assert health_report.validate_report(report) == []
    assert report["clients"]["b"]["quarantined"] == 2
    assert report["summary"]["quarantines"] == 2
    assert report["summary"]["quarantined_clients"] == ["b"]
    broken = json.loads(json.dumps(report))
    broken["clients"]["b"]["quarantined"] = "2"
    assert any(
        "quarantined" in v for v in health_report.validate_report(broken)
    )


def test_health_report_joins_privacy_summary(tmp_path):
    """Round 23: the privacy plane's summary (fed.rounds.privacy_summary)
    joins the report behind --privacy — dp/secagg blocks typed (real
    bools, not ints), per-client epsilon finite-nonnegative, and a
    headline max_epsilon that disagrees with its own per-client rows trips
    the guard (the one accounting-drift class this join exists to catch)."""
    from fedcrack_tpu.fed import rounds as R
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.tools import health_report

    cfg = FedConfig(
        cohort_size=2, max_rounds=2, registration_window_s=1.0,
        dp_clip_norm=1.0, dp_noise_multiplier=1.1, dp_sample_rate=0.01,
        dp_steps_per_round=4, dp_delta=1e-5,
    )
    state = R.initial_state(cfg, {"w": np.zeros(6, np.float32)})
    for n in ("a", "b"):
        state, _ = R.transition(state, R.Ready(cname=n, now=0.0))
    state = R._advance_time(state, 2.0)
    blob = tree_to_bytes({"w": np.full(6, 0.5, np.float32)})
    rnd = state.current_round
    for n in ("a", "b"):
        state, _ = R.transition(
            state,
            R.TrainDone(cname=n, blob=blob, num_samples=10, round=rnd, now=3.0),
        )
    ledger_path = str(tmp_path / "ledger.jsonl")
    hl.write_ledger_jsonl(state.ledger, ledger_path)
    privacy_path = str(tmp_path / "privacy.json")
    with open(privacy_path, "w", encoding="utf-8") as f:
        json.dump(R.privacy_summary(state), f)
    out_path = str(tmp_path / "report.json")
    rc = health_report.main(
        ["--ledger", ledger_path, "--privacy", privacy_path, "--out", out_path]
    )
    assert rc == 0
    with open(out_path, encoding="utf-8") as f:
        report = json.load(f)
    assert health_report.validate_report(report) == []
    dp = report["privacy"]["dp"]
    assert dp["enabled"] is True and dp["noise_multiplier"] == 1.1
    assert dp["clients"]["a"]["steps"] == 4
    assert dp["max_epsilon"] == max(
        c["epsilon"] for c in dp["clients"].values()
    )
    assert report["privacy"]["secagg"]["enabled"] is False
    # A report WITHOUT the artifact records absence, not a plausible block.
    assert health_report.build_report(ledger_path)["privacy"] is None
    # Headline/per-client disagreement is the accounting bug the guard
    # exists for.
    broken = json.loads(json.dumps(report))
    broken["privacy"]["dp"]["max_epsilon"] = 99.0
    assert any(
        "max_epsilon" in v for v in health_report.validate_report(broken)
    )
    # enabled must be a REAL bool — a 1 from a sloppy writer fails.
    intbool = json.loads(json.dumps(report))
    intbool["privacy"]["dp"]["enabled"] = 1
    assert any(
        "wants bool" in v for v in health_report.validate_report(intbool)
    )
    # Non-finite epsilon never ships.
    inf = json.loads(json.dumps(report))
    inf["privacy"]["dp"]["clients"]["a"]["epsilon"] = float("nan")
    assert any(
        "finite" in v for v in health_report.validate_report(inf)
    )


# ---------- the robust-aggregation A/B drill: response layer, end to end ----


def test_robust_aggregation_drill_end_to_end():
    """The round-21 acceptance chain in one artifact: the identical
    poisoned cohort cliffs the canary under FedAvg but holds IoU >= 0.9
    under trimmed-mean / Krum / the ledger-coupled quarantine, with drag
    cut >= 10x; the quarantined flush-trigger is resynced NOT_WAIT; the
    colluding-minority variant is beaten by every robust arm; and the
    exclusion shows up in the joined health report."""
    from fedcrack_tpu.tools.chaos_drill import run_robust_aggregation_drill

    out = run_robust_aggregation_drill()
    assert out["fedavg_cliffed"]
    assert out["robust_arms_hold"]
    assert out["drag_reduced_10x"]
    arms = out["arms"]
    assert arms["fedavg"]["canary_iou"] < 0.5 <= out["reference_iou"]
    for name in ("trimmed_mean", "krum", "fedavg_quarantine"):
        assert arms[name]["canary_iou"] >= 0.9
        assert arms[name]["drag_reduction_vs_fedavg"] >= 10.0
    q = arms["fedavg_quarantine"]
    assert q["quarantined"] and "c" in q["quarantined"]
    assert q["poisoned_resynced_not_wait"] and q["clean_global_attached"]
    assert q["ledger_quarantined_count"] == 1 and q["honest_not_quarantined"]
    assert all(out["colluding"]["colluders_beaten"].values())
    hp = out["health_report"]
    assert hp["schema_violations"] == [] and hp["exclusion_visible"]

    # The drill's artifact is exactly what bench.py commits: schema-check
    # it with the same validator the committed artifact tests use.
    import bench

    assert bench.validate_detail({"robust_aggregation": out}) == []
